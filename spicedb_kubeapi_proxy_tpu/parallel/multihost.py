"""Multi-host (DCN) execution of the sharded engine.

The reference's distributed story is NCCL/MPI-free — gRPC between
processes (SURVEY §2.5). The TPU-native analog has two tiers:

- WITHIN a slice: XLA collectives over ICI inside the shard_map'd
  fixpoint (`parallel/sharded.py`) — no host involvement per hop.
- ACROSS hosts: the SAME shard_map over a global mesh spanning every
  process's devices, with XLA routing the collectives over DCN.
  JAX's multi-controller SPMD model requires every process to execute
  the same program on the same inputs; :func:`init_distributed` wires a
  process into the coordination service, and ``make_mesh`` (mesh.py)
  builds over ``jax.devices()`` — the GLOBAL device list — when asked.

`tests/test_multihost.py` validates the full engine query path (bulk
load, dense blocks, collective joins, incremental writes) over two OS
processes with Gloo carrying the cross-process collectives — the CPU
stand-in for DCN.

Serving integration: a multi-host engine host is ONE TCP-serving leader
process plus follower processes that execute the same program in
lockstep (the SPMD contract). The leader wraps its engine in
:class:`MirroredEngine`, which SERIALIZES every state mutation and
device dispatch, publishes each action to subscribed followers over the
ordinary engine protocol (``mirror_subscribe``, a server-push stream
like watches), resolves wall clocks to concrete values before
publishing, and only then executes locally; followers replay the stream
1:1 (:func:`follower_loop`). XLA collectives synchronize the actual
compute — a follower that falls behind simply makes the leader's next
collective wait. Validated end-to-end by
``tests/test_multihost.py::test_multihost_serving_leader_follower``:
leader + follower processes, a client driving real traffic over TCP.

Failure model: SPMD is all-or-nothing — with a dead follower the
leader's next collective FAILS or BLOCKS depending on the transport
(Gloo errors fast — the client sees an engine error; DCN may stall
until its timeout) but never answers, and the leader process survives.
Deploy the process set as a unit; an orchestrator restart heals it
(validated by tests/test_multihost.py::
test_multihost_follower_death_blocks_leader_restart_heals). Reads that
touch no device (store reads, watch_gate, revision) are served
leader-locally without mirroring.

The SAME mirror machinery also carries the primary/replica FAILOVER
deployment (`--peers`, parallel/failover.py): there MirroredEngine
runs with ``mirror_queries=False`` (no SPMD lockstep — queries serve
leader-locally) and ``sync_replication=True`` (a write's ack waits for
every live follower to apply AND journal its frame), every frame/
heartbeat/catch-up/ack carries a fenced ``term``, and a dead LEADER is
survivable: a follower promotes and clients re-resolve.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

import jax

from ..utils.metrics import metrics

log = logging.getLogger("sdbkp.multihost")


class MultiHostError(RuntimeError):
    pass


class StaleTermError(MultiHostError):
    """A mirror frame (or subscription ack) carried a term OLDER than the
    one this process has already adopted: a deposed leader's late output.
    Fencing rejects it — applying it would fork the store lineages."""


class LeaderLost(MultiHostError):
    """The mirror stream's leader stopped heartbeating (or the connection
    died) while the follower was configured to treat that as a failover
    trigger rather than an orchestrator-restart event."""


def fence_term(frame_term, current_term: int) -> int:
    """The ONE fencing check: given the term stamped on an incoming
    mirror artifact (frame, heartbeat, catch-up cut, subscription ack;
    ``None`` = a pre-term peer) and the highest term this process has
    adopted, return the possibly-advanced current term — or raise
    :class:`StaleTermError` (counting it) when the artifact belongs to a
    deposed lineage."""
    if frame_term is None:
        return current_term
    frame_term = int(frame_term)
    if frame_term < current_term:
        metrics.counter("mirror_frames_rejected_stale_term_total").inc()
        raise StaleTermError(
            f"rejecting mirror frame from deposed term {frame_term} "
            f"(current term {current_term})")
    return frame_term


def parse_distributed_spec(spec: str) -> tuple[str, int, int]:
    """``coordinator_host:port,num_processes,process_id`` -> parsed
    triple. The ONE owner of this format — the engine-host CLI also
    consults it (follower detection) before initializing anything."""
    parts = spec.split(",")
    if len(parts) != 3:
        raise MultiHostError(
            f"--distributed {spec!r}: expected "
            "coordinator_host:port,num_processes,process_id")
    coordinator, num, pid = parts
    try:
        n, p = int(num), int(pid)
    except ValueError:
        raise MultiHostError(
            f"--distributed {spec!r}: num_processes and process_id "
            "must be integers") from None
    if not (0 <= p < n):
        raise MultiHostError(
            f"--distributed {spec!r}: process_id must be in [0, {n})")
    return coordinator, n, p


def init_distributed(spec: str) -> None:
    """Join the JAX distributed coordination service (spec format:
    :func:`parse_distributed_spec`; the engine-host CLI exposes it as
    ``--distributed``)."""
    coordinator, n, p = parse_distributed_spec(spec)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n, process_id=p)


class MirroredEngine:
    """Leader-side engine wrapper for multi-host serving.

    Every state mutation and device-dispatching query is (a) serialized
    under one lock — SPMD processes must execute identical dispatch
    sequences, so concurrent request handlers are ordered here — and
    (b) published to follower subscribers BEFORE executing locally, with
    wall clocks resolved to concrete values (``now=None`` would read a
    different clock on every process). Device-free reads pass straight
    through to the inner engine.

    The proxy-facing surface matches :class:`~..engine.engine.Engine`
    closely enough for EngineServer and the authz layers (check_bulk,
    lookup_resources[_mask], write/delete/read, watch, store, gate)."""

    def __init__(self, engine, min_subscribers: int = 0,
                 join_timeout: float = 300.0, term: int = 0,
                 mirror_queries: bool = True,
                 sync_replication: bool = False,
                 replication_timeout: float = 10.0,
                 min_sync_replicas: int = 0):
        self.engine = engine
        self._lock = threading.Lock()
        self._subs: list[queue.Queue] = []
        self._subs_lock = threading.Lock()
        self._seq = 0
        # fenced term (leader failover, parallel/failover.py): stamped
        # into every published frame, heartbeat, and catch-up cut so a
        # deposed leader's late output is rejectable. 0 = the legacy SPMD
        # lockstep deployment, which never changes leaders.
        self.term = int(term)
        # revision at promotion: shared history ends here. A subscriber
        # resuming from a REVISION past this point with a TERM before
        # ours lived through writes this lineage fenced off — the
        # general form of PR 3's "follower ahead of leader" rule.
        self.baseline_revision = int(engine.revision)
        # failover (primary/replica) mode mirrors only MUTATIONS: there
        # is no SPMD collective lockstep to feed, so queries serve
        # leader-locally (decision cache and batching stay effective)
        self._mirror_queries = mirror_queries
        # sync replication: a mutation does not return to the caller
        # until every live subscriber has ACKED its frame (having
        # journaled it under the follower's own fsync policy) — the
        # no-acked-write-lost guarantee leader SIGKILL failover needs
        self._sync_replication = sync_replication
        self._replication_timeout = replication_timeout
        # durability floor: with fewer live subscribers than this, writes
        # FAIL CLOSED instead of acking unreplicated (the window a
        # partitioned leader would otherwise silently lose on demotion).
        # 0 = availability over redundancy (a 1-of-2 set keeps serving).
        self._min_sync_replicas = int(min_sync_replicas)
        self._ack_cond = threading.Condition(self._subs_lock)
        self._acked: dict[int, int] = {}  # id(queue) -> highest acked seq
        # id(queue) -> catch-up cut seq: frames at or before the cut are
        # NOT this subscriber's responsibility (the transfer covers
        # them) — but that is responsibility accounting, not durability:
        # only a real ack (the follower applied AND journaled) counts
        # toward the min-sync floor
        self._join_cut: dict[int, int] = {}
        # JOIN BARRIER: a leader must not execute (or drop!) any action
        # before every follower is subscribed — writes never touch the
        # device, so nothing else would stop an early client write from
        # silently missing a follower and desyncing the stores. _publish
        # blocks until the expected follower count has joined.
        self._min_subs = min_subscribers
        self._join_timeout = join_timeout
        self._joined = threading.Event()
        if min_subscribers <= 0:
            self._joined.set()

    # -- follower stream -----------------------------------------------------

    @property
    def mirror_seq(self) -> int:
        with self._subs_lock:
            return self._seq

    def subscribe(self) -> "queue.Queue[dict]":
        q: queue.Queue = queue.Queue()
        with self._subs_lock:
            self._subs.append(q)
            # frames sequenced before this join are not the new
            # subscriber's RESPONSIBILITY (they were never sent to it;
            # a catch-up cut supersedes this with its own seq) — but
            # responsibility is not durability: _acked starts at 0 and
            # only real acks ever satisfy the min-sync floor
            self._acked[id(q)] = 0
            self._join_cut[id(q)] = self._seq
            if len(self._subs) >= self._min_subs:
                self._joined.set()
        return q

    def subscribe_with_catchup(self, from_revision: int,
                               subscriber_term: Optional[int] = None):
        """(queue, catch-up meta, optional state payload) for a RESUMING
        follower (``mirror_subscribe`` with ``from_revision``).

        The queue registers FIRST — a plain :meth:`subscribe`, so the
        join barrier counts this follower immediately and a leader
        parked in ``_publish`` waiting for it can proceed (taking the
        mirror lock before subscribing would deadlock that barrier).
        The consistent cut then happens under the mirror lock, which
        excludes in-flight publish+execute pairs: the catch-up state
        reflects every action sequenced at or before ``meta["seq"]``,
        and the follower SKIPS queued frames with ``seq <=`` that value
        (they are already inside the catch-up) — nothing double-applies,
        nothing is missed.

        Catch-up forms, cheapest first: already-current (nothing),
        effects replay from the leader's retained watch history, or a
        full compacted state transfer (the follower's revision predates
        retained history or a bulk load)."""
        from dataclasses import asdict

        from ..engine.store import OP_DELETE, StoreError

        q = self.subscribe()
        with self._lock:
            with self._subs_lock:
                seq = self._seq
                # the catch-up cut covers every frame at or before it,
                # and the follower rightly never acks frames it skips —
                # record the cut so a sync-replicated write racing this
                # join neither stalls a full replication timeout nor
                # kicks the freshly joined follower. This is NOT an ack:
                # the transfer hasn't reached the follower yet, so it
                # must not count toward the min-sync durability floor
                # (the follower acks the cut itself once the catch-up
                # is applied and journaled — follower_loop).
                self._join_cut[id(q)] = seq
                self._ack_cond.notify_all()
            store = self.engine.store
            rev = store.revision
            # the general fencing form of the "follower ahead of leader"
            # rule below: a subscriber from a DEPOSED term whose revision
            # runs past our promotion baseline lived through writes this
            # lineage fenced off — its revision NUMBERS overlap ours but
            # name different history, so neither "already current" nor an
            # effects replay is sound. Full state, unconditionally.
            deposed = (subscriber_term is not None and self.term
                       and int(subscriber_term) < self.term
                       and from_revision > self.baseline_revision)
            if deposed:
                log.warning(
                    "subscriber resumes from deposed term %s at revision "
                    "%d past promotion baseline %d (term %d); sending "
                    "full state", subscriber_term, from_revision,
                    self.baseline_revision, self.term)
            if not deposed and from_revision == rev:
                return q, {"revision": rev, "seq": seq,
                           "term": self.term}, None
            if not deposed and from_revision > rev:
                # the follower claims MORE history than the leader has:
                # a lost leader disk or a rolled-back fsync window — the
                # lineages diverged, and "already current" would freeze
                # the divergence. Force a full state transfer onto the
                # leader's lineage (the source of truth for serving).
                log.warning(
                    "follower resume revision %d is ahead of leader "
                    "revision %d (diverged lineage); sending full state",
                    from_revision, rev)
            elif not deposed and from_revision >= store.unlogged_revision:
                try:
                    records = store.watch_since(from_revision)
                except StoreError:
                    records = None
                if records is not None:
                    effects = [
                        {"op": "delete" if r.op == OP_DELETE else "touch",
                         "rel": asdict(r.rel)}
                        for r in records
                    ]
                    return q, {"revision": rev, "seq": seq,
                               "term": self.term,
                               "effects": effects}, None
            # full state transfer: COLLECT under the lock (the arrays are
            # immutable copies cut consistently with `seq`)...
            cols, meta = store._collect_state()
        # ...but compress OUTSIDE it — savez_compressed over a multi-GB
        # store must not stall every leader write and mirrored query
        payload = store.encode_state(cols, meta)
        return q, {"revision": int(meta["revision"]), "seq": seq,
                   "term": self.term, "state": True}, payload

    def unsubscribe(self, q) -> None:
        with self._subs_lock:
            if q in self._subs:
                self._subs.remove(q)
            self._acked.pop(id(q), None)
            self._join_cut.pop(id(q), None)
            # a write parked in _wait_replicated stops waiting for a
            # subscriber that no longer exists
            self._ack_cond.notify_all()

    def close_subscribers(self) -> None:
        """Terminate every mirror stream (deposed-leader demotion,
        parallel/failover.py): a follower still subscribed here would
        otherwise keep receiving valid old-term heartbeats from the
        frozen wrapper and never notice the leadership change. The None
        sentinel makes each connection handler close its stream; the
        follower sees LeaderLost and re-elects toward the new lineage."""
        with self._subs_lock:
            for q in self._subs:
                q.put(None)
            self._subs.clear()
            self._acked.clear()
            self._join_cut.clear()
            self._ack_cond.notify_all()

    def record_ack(self, q, seq: int, term: Optional[int] = None) -> None:
        """A follower acknowledged every frame up to ``seq`` (having
        applied AND journaled them). Cross-subscription confusion is
        impossible by construction (``q`` is the connection's own queue
        object, not a wire-carried id), so only a FUTURE-term ack is
        rejected as nonsensical — an older-term ack is legitimate
        lineage continuity when an equal-term conflict bumped this
        wrapper's term mid-flight, and dropping it would stall the
        write and kick a healthy follower."""
        if term is not None and self.term and int(term) > self.term:
            return
        with self._subs_lock:
            if id(q) in self._acked:
                self._acked[id(q)] = max(self._acked[id(q)], int(seq))
                self._ack_cond.notify_all()

    def _wait_replicated(self, seq: int) -> None:
        """Block until every LIVE subscriber has acked ``seq``. A
        subscriber that dies mid-wait stops being waited on when its
        connection handler unsubscribes it; one that stalls past the
        replication timeout is dropped (it rejoins through catch-up) so a
        wedged follower bounds, not wedges, the leader's write path.
        When dropping laggards leaves fewer acked replicas than the
        ``min_sync_replicas`` floor, the write FAILS instead of acking —
        the mutation is applied locally (outcome: unknown to the
        caller, exactly like a write whose response connection died),
        never acknowledged as durable when it is not."""
        import time as _time

        from ..obs.trace import tracer
        from ..utils.metrics import metrics

        t_wait0 = _time.perf_counter()
        ack_span = tracer.begin("replication_ack_wait", seq=seq)
        try:
            self._wait_replicated_inner(seq)
        finally:
            metrics.histogram("engine_replication_ack_seconds").observe(
                _time.perf_counter() - t_wait0)
            if ack_span is not None:
                ack_span.finish()

    def _wait_replicated_inner(self, seq: int) -> None:
        import time as _time

        deadline = _time.monotonic() + self._replication_timeout
        # ids observed acking >= seq at ANY point — an ack is a durable
        # journal entry on that replica, so it still counts toward the
        # floor if the follower then rotates away; a follower that
        # UNSUBSCRIBES WITHOUT acking (connection died mid-frame) never
        # enters this set, so the floor check below catches it even
        # though the no-laggards exit fires the moment it departs
        satisfied: set[int] = set()
        with self._subs_lock:
            while True:
                laggards = []
                for q in self._subs:
                    if self._acked.get(id(q), 0) >= seq:
                        satisfied.add(id(q))
                    elif self._join_cut.get(id(q), -1) >= seq:
                        # the frame is inside this joiner's catch-up cut:
                        # not a laggard (don't stall or kick it), but not
                        # durably acked either — it joins `satisfied`
                        # only via its real post-catch-up cut ack
                        pass
                    else:
                        laggards.append(q)
                # done only when nobody is behind AND the durability
                # floor is met — a joiner mid-catch-up is not a laggard
                # but hasn't journaled yet, so a floored write keeps
                # waiting (bounded) for its post-catch-up ack
                if not laggards \
                        and len(satisfied) >= self._min_sync_replicas:
                    break
                left = deadline - _time.monotonic()
                if left <= 0:
                    for q in laggards:
                        log.warning(
                            "dropping mirror subscriber %d frames behind "
                            "after %.1fs replication timeout (it can "
                            "rejoin via catch-up)",
                            seq - self._acked.get(id(q), 0),
                            self._replication_timeout)
                        self._subs.remove(q)
                        self._acked.pop(id(q), None)
                        # a None sentinel makes the connection handler
                        # close the stream — the follower must SEE the
                        # drop (a silently unfed queue would heartbeat
                        # forever while diverging)
                        q.put(None)
                    self._ack_cond.notify_all()
                    break
                self._ack_cond.wait(left)
        if len(satisfied) < self._min_sync_replicas:
            from ..engine.store import StoreError

            raise StoreError(
                f"write replicated to only {len(satisfied)} replica(s) "
                f"within {self._replication_timeout:.1f}s, below the "
                f"min-sync-replicas floor of {self._min_sync_replicas}; "
                "treating the outcome as unknown (applied locally, not "
                "acknowledged as durable)")

    def _publish(self, method: str, payload: dict,
                 blob: Optional[bytes] = None) -> Optional[int]:
        """Serialize the action ONCE into wire bytes and fan the same
        bytes object out to every subscriber queue — at N followers the
        leader must not pay N JSON encodes per device dispatch (measured
        -33%/-52% leader throughput at 1/3 followers before this;
        bench_results/multihost_r5_cpu.json). ``blob`` rides a binary
        frame (meta + payload) for the hot check_bulk item batches.
        Returns the frame's sequence number, or None when nobody was
        subscribed (nothing to wait replicated on)."""
        from ..engine.remote import BinaryResult, _pack, _pack_binary

        if not self._joined.wait(self._join_timeout):
            raise MultiHostError(
                f"{self._min_subs} follower(s) did not subscribe within "
                f"{self._join_timeout:.0f}s; refusing to serve (an "
                "unmirrored action would silently desync the stores)")
        with self._subs_lock:
            subs = list(self._subs)
            self._seq += 1
            seq = self._seq
            if not subs:
                # nobody mirroring (single-host MirroredEngine, or every
                # follower already gone): skip serialization entirely —
                # seq still advances; a later joiner baselines on the
                # first frame it receives (and must join before traffic
                # to share store state, per the join-barrier contract)
                return None
        # serialize OUTSIDE _subs_lock: a multi-MB check_bulk encode must
        # not block subscribe()/unsubscribe() (a rejoining follower's join
        # barrier would wait out encode time per batch). Frame ordering is
        # unaffected — every _publish call site already serializes on the
        # engine-level self._lock.
        frame = {"seq": seq, "method": method, **payload}
        if self.term:
            frame["term"] = self.term
        if blob is None:
            wire = _pack({"ok": True, "frame": frame})
        else:
            blob = blob() if callable(blob) else blob
            wire = _pack_binary(
                BinaryResult({"ok": True, "frame": frame}, blob))
        for q in subs:
            q.put(wire)
        return seq

    # -- mirrored mutations --------------------------------------------------

    def _require_replicas(self) -> None:
        """Fail a mutation CLOSED when the live subscriber count is below
        the configured durability floor — an ack the leader could not
        replicate is an ack a failover may silently discard."""
        if not self._sync_replication or self._min_sync_replicas <= 0:
            return
        from ..engine.store import StoreError

        with self._subs_lock:
            n = len(self._subs)
        if n < self._min_sync_replicas:
            raise StoreError(
                f"only {n} live replica(s), below the min-sync-replicas "
                f"floor of {self._min_sync_replicas}: refusing the write "
                "(an unreplicated ack would not survive leader failover)")

    def _write_headroom(self, n_records: int) -> None:
        """Overlay back-pressure must run BEFORE the frame is published:
        a shed after publish would leave followers holding a write the
        leader never applied (a silent lineage fork). The local apply —
        and every follower's replay (apply_mirror_frame) — then runs
        with the headroom gate off: once published, the mutation is
        committed to the replication stream and MUST land everywhere,
        even if the overlay overflows into a counted fallback recompile."""
        hr = getattr(self.engine, "_write_headroom", None)
        if hr is not None:
            hr(n_records)

    def write_relationships(self, ops, preconditions=(), *,
                            _headroom: bool = True):
        from ..engine.remote import _rel_to_dict
        from dataclasses import asdict

        if _headroom:
            self._write_headroom(len(ops))
        self._require_replicas()
        with self._lock:
            seq = self._publish("write_relationships", {
                "ops": [{"op": o.op, "rel": _rel_to_dict(o.rel)}
                        for o in ops],
                "preconditions": [
                    {"filter": asdict(p.filter),
                     "must_exist": p.must_exist}
                    for p in preconditions],
            })
            result = self.engine.write_relationships(
                list(ops), list(preconditions), _headroom=False)
        self._maybe_wait(seq)
        return result

    def delete_relationships(self, f, preconditions=(), *,
                             _headroom: bool = True):
        from dataclasses import asdict

        if _headroom:
            self._write_headroom(1)
        self._require_replicas()
        with self._lock:
            seq = self._publish("delete_relationships", {
                "filter": asdict(f),
                "preconditions": [
                    {"filter": asdict(p.filter),
                     "must_exist": p.must_exist}
                    for p in preconditions],
            })
            result = self.engine.delete_relationships(
                f, list(preconditions), _headroom=False)
        self._maybe_wait(seq)
        return result

    def bulk_load(self, rels_cols):
        # columnar payloads are huge: ride the binary-payload frame (the
        # npz columnar codec, persistence/codec.py) like the hot
        # check_bulk batches do, instead of serializing one JSON string
        # per cell — a 1M-relationship load is one C-speed encode, built
        # LAZILY so a subscriber-less leader pays nothing
        from ..persistence.codec import encode_bulk_cols

        self._require_replicas()
        with self._lock:
            seq = self._publish("bulk_load", {},
                                blob=lambda: encode_bulk_cols(rels_cols))
            result = self.engine.bulk_load(rels_cols)
        self._maybe_wait(seq)
        return result

    def _maybe_wait(self, seq: Optional[int]) -> None:
        # outside the mirror lock on purpose: waiting for follower acks
        # must not serialize every other mirrored op behind one write's
        # replication round trip
        if not self._sync_replication:
            return
        if seq is None:
            # nobody was subscribed at publish time. _require_replicas
            # ran before the mirror lock, so the last follower can
            # vanish in between — the floor must hold on the PUBLISH
            # outcome too, or that race acks an unreplicated write
            if self._min_sync_replicas > 0:
                from ..engine.store import StoreError

                raise StoreError(
                    "write published to 0 replicas (the last follower "
                    "left mid-write), below the min-sync-replicas floor "
                    f"of {self._min_sync_replicas}; treating the outcome "
                    "as unknown (applied locally, not acknowledged as "
                    "durable)")
            return
        self._wait_replicated(seq)

    # -- mirrored queries ----------------------------------------------------

    def check_bulk(self, items, now=None, context=None):
        return self.check_bulk_async(items, now=now,
                                     context=context).result()

    def check_bulk_async(self, items, now=None, context=None):
        import time as _time

        if not self._mirror_queries:
            # failover (primary/replica) mode: no SPMD lockstep to feed —
            # queries serve leader-locally (cache/batching stay live)
            return self.engine.check_bulk_async(items, now=now,
                                                context=context)
        if now is None:
            now = _time.time()  # concrete BEFORE publishing
        # normalize ONCE and execute the normalized items locally too —
        # publishing a str-coerced copy while executing the raw items
        # would let a non-str field produce different dispatch groups on
        # leader and follower
        items = [normalize_check_item(it) for it in items]
        with self._lock:
            # the firehose path: items ride a flat binary payload built
            # LAZILY — _publish only materializes it when subscribers
            # exist (the encode is the dominant publish cost)
            self._publish("check_bulk", {"now": now, "ctx": context},
                          blob=lambda: encode_check_items(items))
            # dispatch inside the lock (ordering), result read outside
            return self.engine.check_bulk_async(items, now=now,
                                                context=context)

    def check(self, item, now=None, context=None):
        return self.check_bulk([item], now=now, context=context)[0]

    def lookup_resources(self, resource_type, permission, subject_type,
                         subject_id, subject_relation=None, now=None,
                         context=None):
        from ..engine.engine import mask_to_ids

        mask, interner = self.lookup_resources_mask(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context)
        return mask_to_ids(mask, interner)

    def lookup_resources_mask(self, resource_type, permission,
                              subject_type, subject_id,
                              subject_relation=None, now=None,
                              context=None):
        return self.lookup_resources_mask_async(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context).result()

    def lookup_resources_mask_async(self, resource_type, permission,
                                    subject_type, subject_id,
                                    subject_relation=None, now=None,
                                    context=None):
        import time as _time

        if not self._mirror_queries:
            return self.engine.lookup_resources_mask_async(
                resource_type, permission, subject_type, subject_id,
                subject_relation, now=now, context=context)
        if now is None:
            now = _time.time()
        with self._lock:
            self._publish("lookup_mask", {
                "resource_type": resource_type, "permission": permission,
                "subject_type": subject_type, "subject_id": subject_id,
                "subject_relation": subject_relation, "now": now,
                "ctx": context,
            })
            return self.engine.lookup_resources_mask_async(
                resource_type, permission, subject_type, subject_id,
                subject_relation, now=now)

    # -- device-free passthrough ---------------------------------------------

    def __getattr__(self, name):
        return getattr(self.engine, name)


def normalize_check_item(it):
    """Leader-side trust boundary: field values arrive from client JSON
    with no type guarantee. Coerce to str (None stays None for the
    subject relation) and use the SAME normalized item for publishing
    and local execution — leader and follower then cannot diverge on a
    field the codec or the interner would treat differently. Fast path:
    items that are already all-str (the normal case) pass through
    untouched."""
    from ..engine import CheckItem

    sr = it.subject_relation
    if type(it.resource_type) is str and type(it.resource_id) is str \
            and type(it.permission) is str \
            and type(it.subject_type) is str \
            and type(it.subject_id) is str \
            and (sr is None or type(sr) is str):
        return it
    return CheckItem(
        str(it.resource_type), str(it.resource_id), str(it.permission),
        str(it.subject_type), str(it.subject_id),
        None if sr is None else str(sr))


def encode_check_items(items) -> bytes:
    """CheckItems -> one FLAT JSON array of 6N fields (None for a missing
    subject relation), utf-8. One C-speed ``json.dumps`` per batch —
    injective for ANY string content (JSON escapes control characters,
    so client-controlled ids round-trip exactly and "" stays distinct
    from None; both matter — the engine groups device dispatches by
    subject key, so a lossy codec would desync SPMD dispatch shapes)
    and ~16% smaller than the old nested list-of-lists frame. A
    hand-rolled length-prefixed binary codec was measured SLOWER than
    this (pure-Python per-field loops cost more than the bytes saved);
    numbers in bench_results/multihost_r5_cpu.json."""
    import json as _json

    flat = []
    for it in items:
        flat += (it.resource_type, it.resource_id, it.permission,
                 it.subject_type, it.subject_id, it.subject_relation)
    return _json.dumps(flat, ensure_ascii=False,
                       separators=(",", ":")).encode()


def decode_check_items(blob: bytes) -> list:
    import json as _json

    from ..engine import CheckItem

    try:
        flat = _json.loads(blob)
    except ValueError:
        raise MultiHostError("malformed check-item payload") from None
    if not isinstance(flat, list) or len(flat) % 6:
        raise MultiHostError("malformed check-item payload")
    return [CheckItem(*flat[i:i + 6]) for i in range(0, len(flat), 6)]


def apply_mirror_frame(engine, frame: dict,
                       blob: Optional[bytes] = None) -> None:
    """Execute one published action on a follower's local engine. The
    caller guarantees in-order delivery (TCP stream). ``blob`` carries
    the compact binary payload for check_bulk frames."""
    from ..engine.engine import SchemaViolation
    from ..engine.store import StoreError

    m = frame["method"]
    try:
        _apply_one(engine, frame, m, blob)
    except (StoreError, SchemaViolation) as e:
        # deterministic engine-level failures (precondition conflicts,
        # schema violations, AlreadyExists) happen IDENTICALLY on the
        # leader — its execution runs after publishing — so the stores
        # stay in sync; a follower must keep replaying, not die and
        # leave the leader's next collective hanging
        log.debug("mirror frame %s failed identically to leader: %s",
                  m, e)


def _apply_one(engine, frame: dict, m: str,
               blob: Optional[bytes] = None) -> None:
    from ..engine import CheckItem
    from ..engine.remote import _filter_from_dict, _rel_from_dict
    from ..engine.store import Precondition, WriteOp

    if m == "write_relationships":
        # _headroom=False: a replicated frame is already committed to
        # the stream — a follower shedding it on overlay back-pressure
        # would silently fork the store lineages. The overlay still
        # absorbs it when it fits; overflow falls back to a counted
        # recompile (and the follower's own compactor, when enabled,
        # folds in the background).
        engine.write_relationships(
            [WriteOp(o["op"], _rel_from_dict(o["rel"]))
             for o in frame["ops"]],
            [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
             for p in frame.get("preconditions", [])],
            _headroom=False)
    elif m == "delete_relationships":
        engine.delete_relationships(
            _filter_from_dict(frame["filter"]),
            [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
             for p in frame.get("preconditions", [])],
            _headroom=False)
    elif m == "bulk_load":
        if blob is not None:
            from ..persistence.codec import decode_bulk_cols

            engine.bulk_load(decode_bulk_cols(blob))
        else:
            # legacy JSON-list frame from an older leader
            import numpy as np

            cols = {}
            for k, v in frame["cols"].items():
                if k == "expiration":
                    cols[k] = np.asarray(
                        [np.nan if x is None else x for x in v],
                        dtype=np.float64)
                else:
                    cols[k] = np.asarray(v, dtype=object)
            engine.bulk_load(cols)
    elif m == "check_bulk":
        items = decode_check_items(blob) if blob is not None \
            else [CheckItem(*it) for it in frame["items"]]
        engine.check_bulk(items, now=frame["now"],
                          context=frame.get("ctx") or None)
    elif m == "lookup_mask":
        engine.lookup_resources_mask(
            frame["resource_type"], frame["permission"],
            frame["subject_type"], frame["subject_id"],
            frame.get("subject_relation"), now=frame["now"],
            context=frame.get("ctx") or None)
    else:
        raise MultiHostError(f"unknown mirror method {m!r}")


def apply_catchup(engine, meta: dict, blob: Optional[bytes]) -> None:
    """Apply a leader catch-up frame on the follower: a full compacted
    state transfer (binary payload) or a concrete effects replay, both
    landing the store exactly at the leader's revision. No-op when the
    follower was already current."""
    if blob is not None:
        persistence = getattr(engine, "_persistence", None)
        if persistence is not None:
            # a full-state transfer is a NEW LINEAGE BASELINE: the local
            # WAL + snapshots describe superseded (possibly fenced-off)
            # history whose revision numbers may overlap the incoming
            # ones — keeping them would make the next boot's replay see
            # revisions go backwards. Rebase: wipe, install, re-journal
            # the baseline as the fresh log's first record.
            persistence.rebase(blob)
        else:
            engine.store.load_state_bytes(blob)
        # a diverged-lineage transfer can land on the SAME revision
        # number with different rows — the revision check alone would
        # keep serving the old lineage's compiled graph (and the old
        # lineage's decision-cache verdicts under colliding revisions)
        if hasattr(engine, "_compiled"):
            with engine._lock:
                engine._compiled = None
        cache = getattr(engine, "_decision_cache", None)
        if cache is not None:
            cache.clear()
        log.info("catch-up: installed leader state at revision %d",
                 engine.store.revision)
        return
    effects = meta.get("effects")
    if effects:
        engine.store.apply_effects(effects, int(meta["revision"]))
        log.info("catch-up: applied %d effects to revision %d",
                 len(effects), engine.store.revision)


# mirror frames that mutate store state (and therefore get follower
# acks under sync replication — query frames advance nothing durable)
MUTATION_METHODS = frozenset(
    {"write_relationships", "delete_relationships", "bulk_load"})


def follower_loop(engine, leader_host: str, leader_port: int,
                  token: Optional[str] = None,
                  ssl_context=None,
                  server_hostname: Optional[str] = None,
                  from_revision: Optional[int] = None,
                  current_term: int = 0,
                  heartbeat_timeout: Optional[float] = None,
                  ack: bool = False,
                  fail_on_loss: bool = False,
                  on_term=None,
                  on_progress=None,
                  connect_deadline: float = 120.0) -> None:
    """Blocking follower: subscribe to the leader's mirror stream and
    replay every action on the local engine — the device dispatches then
    meet the leader's inside the shard_map collectives. Returns when
    the leader closes the connection; raises on protocol errors.
    ``ssl_context`` wraps the subscription in TLS (the leader serves the
    ordinary engine endpoint, which is TLS unless --engine-insecure).

    ``from_revision`` (a restarting follower's own recovered revision —
    ``engine.revision`` after ``enable_persistence``) asks the leader for
    catch-up: the delta since that revision arrives as the stream's first
    frame (effects replay or a full state transfer) before live mirror
    frames, so rejoining needs no manual bulk_load and no unbroken
    process-lifetime stream.

    Failover-mode knobs (parallel/failover.py is the one caller):
    ``current_term`` fences every term-stamped artifact on the stream
    (:func:`fence_term`; ``on_term`` fires when a HIGHER term is adopted
    so the caller can persist it); ``heartbeat_timeout`` shrinks the
    dead-leader detection window and surfaces it as :class:`LeaderLost`
    (as does a dropped connection, when ``fail_on_loss``); ``ack`` sends
    per-mutation acknowledgements back up the stream (the leader's sync
    replication waits on them — the frame is applied AND journaled under
    this store's fsync policy before the ack leaves); ``on_progress``
    receives the follower's lag in frames behind the leader's heartbeat
    sequence."""
    import socket
    import struct
    import time as _time

    from ..engine.remote import EngineServer, _pack, _read_frame_sync

    # the leader binds its port AFTER the symmetric jax.distributed
    # startup, so the follower may dial first: retry refusals briefly
    deadline = _time.monotonic() + connect_deadline
    while True:
        try:
            s = socket.create_connection((leader_host, leader_port),
                                         timeout=5)
            break
        except OSError:
            if _time.monotonic() > deadline:
                raise MultiHostError(
                    f"leader {leader_host}:{leader_port} never came up")
            _time.sleep(0.25)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if ssl_context is not None:
        try:
            s = ssl_context.wrap_socket(
                s, server_hostname=server_hostname or leader_host)
        except Exception:
            s.close()
            raise
    # heartbeats arrive every PUSH_HEARTBEAT on idle streams; anything
    # slower means a dead leader, not an idle one (a None timeout would
    # leave a partitioned follower blocked forever, invisible to its
    # supervisor)
    if heartbeat_timeout is None:
        heartbeat_timeout = EngineServer.PUSH_HEARTBEAT * 3 + 5.0
    s.settimeout(heartbeat_timeout)
    msg = {"op": "mirror_subscribe"}
    if from_revision is not None:
        msg["from_revision"] = int(from_revision)
    if current_term:
        msg["term"] = int(current_term)
    if token:
        msg["token"] = token

    def adopt(frame_term):
        nonlocal current_term
        new = fence_term(frame_term, current_term)
        if new > current_term:
            current_term = new
            if on_term is not None:
                on_term(new)

    try:
        s.sendall(_pack(msg))
        ack_frame = _read_frame_sync(s)
        if isinstance(ack_frame, tuple) or not ack_frame.get("ok"):
            raise MultiHostError(f"mirror subscribe rejected: {ack_frame}")
        adopt((ack_frame.get("result") or {}).get("term"))
        expect = None
        skip_upto = None
        applied_seq = 0
        while True:
            try:
                frame = _read_frame_sync(s)
            except TimeoutError:
                metrics.counter("mirror_heartbeat_misses_total").inc()
                raise LeaderLost(
                    f"leader {leader_host}:{leader_port} missed its "
                    f"heartbeat window ({heartbeat_timeout:.1f}s)"
                ) from None
            blob = None
            if isinstance(frame, tuple):
                # binary mirror frame: (meta, payload) — the hot
                # check_bulk batches ride a compact payload
                frame, blob = frame
            if not frame.get("ok"):
                raise MultiHostError(f"mirror stream error: {frame}")
            if frame.get("hb"):
                adopt(frame.get("term"))
                hb_seq = frame.get("seq")
                if on_progress is not None and hb_seq is not None:
                    on_progress(max(0, int(hb_seq) - applied_seq))
                continue  # idle-stream liveness heartbeat
            if "catchup" in frame:
                adopt(frame["catchup"].get("term"))
                apply_catchup(engine, frame["catchup"], blob)
                # actions sequenced at or before the cut are inside the
                # catch-up state; queued frames up to it must be skipped
                skip_upto = frame["catchup"].get("seq")
                applied_seq = int(skip_upto or 0)
                if ack and applied_seq:
                    # the transfer is applied AND journaled (rebase /
                    # effects both run the store's journal hook): every
                    # frame the cut covers is now durable HERE — ack it
                    # so floored writes that raced the join get their
                    # durability credit
                    s.sendall(_pack({"ack": applied_seq,
                                     "term": current_term}))
                continue
            payload = frame["frame"]
            adopt(payload.get("term"))
            # first frame sets the baseline (a leader cannot have served
            # traffic before followers joined — its collectives would
            # have blocked — so nothing real precedes it); after that the
            # stream must be gap-free
            expect = payload["seq"] if expect is None else expect + 1
            if payload["seq"] != expect:
                raise MultiHostError(
                    f"mirror gap: expected seq {expect}, "
                    f"got {payload['seq']}")
            if skip_upto is not None and payload["seq"] <= skip_upto:
                continue  # already covered by the catch-up cut
            apply_mirror_frame(engine, payload, blob)
            applied_seq = int(payload["seq"])
            if ack and payload["method"] in MUTATION_METHODS:
                # applied AND journaled (the store's journal hook runs
                # under its write lock inside the apply): safe to credit
                s.sendall(_pack({"ack": applied_seq,
                                 "term": current_term}))
    except (ConnectionResetError, struct.error):
        if fail_on_loss:
            raise LeaderLost(
                f"leader {leader_host}:{leader_port} closed the mirror "
                "stream") from None
        return  # leader went away: the process set restarts as a unit
    finally:
        s.close()
