"""Multi-host (DCN) execution of the sharded engine.

The reference's distributed story is NCCL/MPI-free — gRPC between
processes (SURVEY §2.5). The TPU-native analog has two tiers:

- WITHIN a slice: XLA collectives over ICI inside the shard_map'd
  fixpoint (`parallel/sharded.py`) — no host involvement per hop.
- ACROSS hosts: the SAME shard_map over a global mesh spanning every
  process's devices, with XLA routing the collectives over DCN.
  JAX's multi-controller SPMD model requires every process to execute
  the same program on the same inputs; :func:`init_distributed` wires a
  process into the coordination service, and ``make_mesh`` (mesh.py)
  builds over ``jax.devices()`` — the GLOBAL device list — when asked.

`tests/test_multihost.py` validates the full engine query path (bulk
load, dense blocks, collective joins, incremental writes) over two OS
processes with Gloo carrying the cross-process collectives — the CPU
stand-in for DCN.

Serving integration: a multi-host engine host is ONE TCP-serving leader
process plus follower processes that execute the same program in
lockstep (the SPMD contract). The leader wraps its engine in
:class:`MirroredEngine`, which SERIALIZES every state mutation and
device dispatch, publishes each action to subscribed followers over the
ordinary engine protocol (``mirror_subscribe``, a server-push stream
like watches), resolves wall clocks to concrete values before
publishing, and only then executes locally; followers replay the stream
1:1 (:func:`follower_loop`). XLA collectives synchronize the actual
compute — a follower that falls behind simply makes the leader's next
collective wait. Validated end-to-end by
``tests/test_multihost.py::test_multihost_serving_leader_follower``:
leader + follower processes, a client driving real traffic over TCP.

Failure model: SPMD is all-or-nothing — with a dead follower the
leader's next collective FAILS or BLOCKS depending on the transport
(Gloo errors fast — the client sees an engine error; DCN may stall
until its timeout) but never answers, and the leader process survives.
Deploy the process set as a unit; an orchestrator restart heals it
(validated by tests/test_multihost.py::
test_multihost_follower_death_blocks_leader_restart_heals). Reads that
touch no device (store reads, watch_gate, revision) are served
leader-locally without mirroring.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

import jax

log = logging.getLogger("sdbkp.multihost")


class MultiHostError(RuntimeError):
    pass


def parse_distributed_spec(spec: str) -> tuple[str, int, int]:
    """``coordinator_host:port,num_processes,process_id`` -> parsed
    triple. The ONE owner of this format — the engine-host CLI also
    consults it (follower detection) before initializing anything."""
    parts = spec.split(",")
    if len(parts) != 3:
        raise MultiHostError(
            f"--distributed {spec!r}: expected "
            "coordinator_host:port,num_processes,process_id")
    coordinator, num, pid = parts
    try:
        n, p = int(num), int(pid)
    except ValueError:
        raise MultiHostError(
            f"--distributed {spec!r}: num_processes and process_id "
            "must be integers") from None
    if not (0 <= p < n):
        raise MultiHostError(
            f"--distributed {spec!r}: process_id must be in [0, {n})")
    return coordinator, n, p


def init_distributed(spec: str) -> None:
    """Join the JAX distributed coordination service (spec format:
    :func:`parse_distributed_spec`; the engine-host CLI exposes it as
    ``--distributed``)."""
    coordinator, n, p = parse_distributed_spec(spec)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n, process_id=p)


class MirroredEngine:
    """Leader-side engine wrapper for multi-host serving.

    Every state mutation and device-dispatching query is (a) serialized
    under one lock — SPMD processes must execute identical dispatch
    sequences, so concurrent request handlers are ordered here — and
    (b) published to follower subscribers BEFORE executing locally, with
    wall clocks resolved to concrete values (``now=None`` would read a
    different clock on every process). Device-free reads pass straight
    through to the inner engine.

    The proxy-facing surface matches :class:`~..engine.engine.Engine`
    closely enough for EngineServer and the authz layers (check_bulk,
    lookup_resources[_mask], write/delete/read, watch, store, gate)."""

    def __init__(self, engine, min_subscribers: int = 0,
                 join_timeout: float = 300.0):
        self.engine = engine
        self._lock = threading.Lock()
        self._subs: list[queue.Queue] = []
        self._subs_lock = threading.Lock()
        self._seq = 0
        # JOIN BARRIER: a leader must not execute (or drop!) any action
        # before every follower is subscribed — writes never touch the
        # device, so nothing else would stop an early client write from
        # silently missing a follower and desyncing the stores. _publish
        # blocks until the expected follower count has joined.
        self._min_subs = min_subscribers
        self._join_timeout = join_timeout
        self._joined = threading.Event()
        if min_subscribers <= 0:
            self._joined.set()

    # -- follower stream -----------------------------------------------------

    def subscribe(self) -> "queue.Queue[dict]":
        q: queue.Queue = queue.Queue()
        with self._subs_lock:
            self._subs.append(q)
            if len(self._subs) >= self._min_subs:
                self._joined.set()
        return q

    def subscribe_with_catchup(self, from_revision: int):
        """(queue, catch-up meta, optional state payload) for a RESUMING
        follower (``mirror_subscribe`` with ``from_revision``).

        The queue registers FIRST — a plain :meth:`subscribe`, so the
        join barrier counts this follower immediately and a leader
        parked in ``_publish`` waiting for it can proceed (taking the
        mirror lock before subscribing would deadlock that barrier).
        The consistent cut then happens under the mirror lock, which
        excludes in-flight publish+execute pairs: the catch-up state
        reflects every action sequenced at or before ``meta["seq"]``,
        and the follower SKIPS queued frames with ``seq <=`` that value
        (they are already inside the catch-up) — nothing double-applies,
        nothing is missed.

        Catch-up forms, cheapest first: already-current (nothing),
        effects replay from the leader's retained watch history, or a
        full compacted state transfer (the follower's revision predates
        retained history or a bulk load)."""
        from dataclasses import asdict

        from ..engine.store import OP_DELETE, StoreError

        q = self.subscribe()
        with self._lock:
            with self._subs_lock:
                seq = self._seq
            store = self.engine.store
            rev = store.revision
            if from_revision == rev:
                return q, {"revision": rev, "seq": seq}, None
            if from_revision > rev:
                # the follower claims MORE history than the leader has:
                # a lost leader disk or a rolled-back fsync window — the
                # lineages diverged, and "already current" would freeze
                # the divergence. Force a full state transfer onto the
                # leader's lineage (the source of truth for serving).
                log.warning(
                    "follower resume revision %d is ahead of leader "
                    "revision %d (diverged lineage); sending full state",
                    from_revision, rev)
            elif from_revision >= store.unlogged_revision:
                try:
                    records = store.watch_since(from_revision)
                except StoreError:
                    records = None
                if records is not None:
                    effects = [
                        {"op": "delete" if r.op == OP_DELETE else "touch",
                         "rel": asdict(r.rel)}
                        for r in records
                    ]
                    return q, {"revision": rev, "seq": seq,
                               "effects": effects}, None
            # full state transfer: COLLECT under the lock (the arrays are
            # immutable copies cut consistently with `seq`)...
            cols, meta = store._collect_state()
        # ...but compress OUTSIDE it — savez_compressed over a multi-GB
        # store must not stall every leader write and mirrored query
        payload = store.encode_state(cols, meta)
        return q, {"revision": int(meta["revision"]), "seq": seq,
                   "state": True}, payload

    def unsubscribe(self, q) -> None:
        with self._subs_lock:
            if q in self._subs:
                self._subs.remove(q)

    def _publish(self, method: str, payload: dict,
                 blob: Optional[bytes] = None) -> None:
        """Serialize the action ONCE into wire bytes and fan the same
        bytes object out to every subscriber queue — at N followers the
        leader must not pay N JSON encodes per device dispatch (measured
        -33%/-52% leader throughput at 1/3 followers before this;
        bench_results/multihost_r5_cpu.json). ``blob`` rides a binary
        frame (meta + payload) for the hot check_bulk item batches."""
        from ..engine.remote import BinaryResult, _pack, _pack_binary

        if not self._joined.wait(self._join_timeout):
            raise MultiHostError(
                f"{self._min_subs} follower(s) did not subscribe within "
                f"{self._join_timeout:.0f}s; refusing to serve (an "
                "unmirrored action would silently desync the stores)")
        with self._subs_lock:
            subs = list(self._subs)
            self._seq += 1
            seq = self._seq
            if not subs:
                # nobody mirroring (single-host MirroredEngine, or every
                # follower already gone): skip serialization entirely —
                # seq still advances; a later joiner baselines on the
                # first frame it receives (and must join before traffic
                # to share store state, per the join-barrier contract)
                return
        # serialize OUTSIDE _subs_lock: a multi-MB check_bulk encode must
        # not block subscribe()/unsubscribe() (a rejoining follower's join
        # barrier would wait out encode time per batch). Frame ordering is
        # unaffected — every _publish call site already serializes on the
        # engine-level self._lock.
        frame = {"seq": seq, "method": method, **payload}
        if blob is None:
            wire = _pack({"ok": True, "frame": frame})
        else:
            blob = blob() if callable(blob) else blob
            wire = _pack_binary(
                BinaryResult({"ok": True, "frame": frame}, blob))
        for q in subs:
            q.put(wire)

    # -- mirrored mutations --------------------------------------------------

    def write_relationships(self, ops, preconditions=()):
        from ..engine.remote import _rel_to_dict
        from dataclasses import asdict

        with self._lock:
            self._publish("write_relationships", {
                "ops": [{"op": o.op, "rel": _rel_to_dict(o.rel)}
                        for o in ops],
                "preconditions": [
                    {"filter": asdict(p.filter),
                     "must_exist": p.must_exist}
                    for p in preconditions],
            })
            return self.engine.write_relationships(
                list(ops), list(preconditions))

    def delete_relationships(self, f, preconditions=()):
        from dataclasses import asdict

        with self._lock:
            self._publish("delete_relationships", {
                "filter": asdict(f),
                "preconditions": [
                    {"filter": asdict(p.filter),
                     "must_exist": p.must_exist}
                    for p in preconditions],
            })
            return self.engine.delete_relationships(f, list(preconditions))

    def bulk_load(self, rels_cols):
        # columnar payloads are huge: ride the binary-payload frame (the
        # npz columnar codec, persistence/codec.py) like the hot
        # check_bulk batches do, instead of serializing one JSON string
        # per cell — a 1M-relationship load is one C-speed encode, built
        # LAZILY so a subscriber-less leader pays nothing
        from ..persistence.codec import encode_bulk_cols

        with self._lock:
            self._publish("bulk_load", {},
                          blob=lambda: encode_bulk_cols(rels_cols))
            return self.engine.bulk_load(rels_cols)

    # -- mirrored queries ----------------------------------------------------

    def check_bulk(self, items, now=None):
        return self.check_bulk_async(items, now=now).result()

    def check_bulk_async(self, items, now=None):
        import time as _time

        if now is None:
            now = _time.time()  # concrete BEFORE publishing
        # normalize ONCE and execute the normalized items locally too —
        # publishing a str-coerced copy while executing the raw items
        # would let a non-str field produce different dispatch groups on
        # leader and follower
        items = [normalize_check_item(it) for it in items]
        with self._lock:
            # the firehose path: items ride a flat binary payload built
            # LAZILY — _publish only materializes it when subscribers
            # exist (the encode is the dominant publish cost)
            self._publish("check_bulk", {"now": now},
                          blob=lambda: encode_check_items(items))
            # dispatch inside the lock (ordering), result read outside
            return self.engine.check_bulk_async(items, now=now)

    def check(self, item, now=None):
        return self.check_bulk([item], now=now)[0]

    def lookup_resources(self, resource_type, permission, subject_type,
                         subject_id, subject_relation=None, now=None):
        from ..engine.engine import mask_to_ids

        mask, interner = self.lookup_resources_mask(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now)
        return mask_to_ids(mask, interner)

    def lookup_resources_mask(self, resource_type, permission,
                              subject_type, subject_id,
                              subject_relation=None, now=None):
        return self.lookup_resources_mask_async(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now).result()

    def lookup_resources_mask_async(self, resource_type, permission,
                                    subject_type, subject_id,
                                    subject_relation=None, now=None):
        import time as _time

        if now is None:
            now = _time.time()
        with self._lock:
            self._publish("lookup_mask", {
                "resource_type": resource_type, "permission": permission,
                "subject_type": subject_type, "subject_id": subject_id,
                "subject_relation": subject_relation, "now": now,
            })
            return self.engine.lookup_resources_mask_async(
                resource_type, permission, subject_type, subject_id,
                subject_relation, now=now)

    # -- device-free passthrough ---------------------------------------------

    def __getattr__(self, name):
        return getattr(self.engine, name)


def normalize_check_item(it):
    """Leader-side trust boundary: field values arrive from client JSON
    with no type guarantee. Coerce to str (None stays None for the
    subject relation) and use the SAME normalized item for publishing
    and local execution — leader and follower then cannot diverge on a
    field the codec or the interner would treat differently. Fast path:
    items that are already all-str (the normal case) pass through
    untouched."""
    from ..engine import CheckItem

    sr = it.subject_relation
    if type(it.resource_type) is str and type(it.resource_id) is str \
            and type(it.permission) is str \
            and type(it.subject_type) is str \
            and type(it.subject_id) is str \
            and (sr is None or type(sr) is str):
        return it
    return CheckItem(
        str(it.resource_type), str(it.resource_id), str(it.permission),
        str(it.subject_type), str(it.subject_id),
        None if sr is None else str(sr))


def encode_check_items(items) -> bytes:
    """CheckItems -> one FLAT JSON array of 6N fields (None for a missing
    subject relation), utf-8. One C-speed ``json.dumps`` per batch —
    injective for ANY string content (JSON escapes control characters,
    so client-controlled ids round-trip exactly and "" stays distinct
    from None; both matter — the engine groups device dispatches by
    subject key, so a lossy codec would desync SPMD dispatch shapes)
    and ~16% smaller than the old nested list-of-lists frame. A
    hand-rolled length-prefixed binary codec was measured SLOWER than
    this (pure-Python per-field loops cost more than the bytes saved);
    numbers in bench_results/multihost_r5_cpu.json."""
    import json as _json

    flat = []
    for it in items:
        flat += (it.resource_type, it.resource_id, it.permission,
                 it.subject_type, it.subject_id, it.subject_relation)
    return _json.dumps(flat, ensure_ascii=False,
                       separators=(",", ":")).encode()


def decode_check_items(blob: bytes) -> list:
    import json as _json

    from ..engine import CheckItem

    try:
        flat = _json.loads(blob)
    except ValueError:
        raise MultiHostError("malformed check-item payload") from None
    if not isinstance(flat, list) or len(flat) % 6:
        raise MultiHostError("malformed check-item payload")
    return [CheckItem(*flat[i:i + 6]) for i in range(0, len(flat), 6)]


def apply_mirror_frame(engine, frame: dict,
                       blob: Optional[bytes] = None) -> None:
    """Execute one published action on a follower's local engine. The
    caller guarantees in-order delivery (TCP stream). ``blob`` carries
    the compact binary payload for check_bulk frames."""
    from ..engine.engine import SchemaViolation
    from ..engine.store import StoreError

    m = frame["method"]
    try:
        _apply_one(engine, frame, m, blob)
    except (StoreError, SchemaViolation) as e:
        # deterministic engine-level failures (precondition conflicts,
        # schema violations, AlreadyExists) happen IDENTICALLY on the
        # leader — its execution runs after publishing — so the stores
        # stay in sync; a follower must keep replaying, not die and
        # leave the leader's next collective hanging
        log.debug("mirror frame %s failed identically to leader: %s",
                  m, e)


def _apply_one(engine, frame: dict, m: str,
               blob: Optional[bytes] = None) -> None:
    from ..engine import CheckItem
    from ..engine.remote import _filter_from_dict, _rel_from_dict
    from ..engine.store import Precondition, WriteOp

    if m == "write_relationships":
        engine.write_relationships(
            [WriteOp(o["op"], _rel_from_dict(o["rel"]))
             for o in frame["ops"]],
            [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
             for p in frame.get("preconditions", [])])
    elif m == "delete_relationships":
        engine.delete_relationships(
            _filter_from_dict(frame["filter"]),
            [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
             for p in frame.get("preconditions", [])])
    elif m == "bulk_load":
        if blob is not None:
            from ..persistence.codec import decode_bulk_cols

            engine.bulk_load(decode_bulk_cols(blob))
        else:
            # legacy JSON-list frame from an older leader
            import numpy as np

            cols = {}
            for k, v in frame["cols"].items():
                if k == "expiration":
                    cols[k] = np.asarray(
                        [np.nan if x is None else x for x in v],
                        dtype=np.float64)
                else:
                    cols[k] = np.asarray(v, dtype=object)
            engine.bulk_load(cols)
    elif m == "check_bulk":
        items = decode_check_items(blob) if blob is not None \
            else [CheckItem(*it) for it in frame["items"]]
        engine.check_bulk(items, now=frame["now"])
    elif m == "lookup_mask":
        engine.lookup_resources_mask(
            frame["resource_type"], frame["permission"],
            frame["subject_type"], frame["subject_id"],
            frame.get("subject_relation"), now=frame["now"])
    else:
        raise MultiHostError(f"unknown mirror method {m!r}")


def apply_catchup(engine, meta: dict, blob: Optional[bytes]) -> None:
    """Apply a leader catch-up frame on the follower: a full compacted
    state transfer (binary payload) or a concrete effects replay, both
    landing the store exactly at the leader's revision. No-op when the
    follower was already current."""
    if blob is not None:
        engine.store.load_state_bytes(blob)
        # a diverged-lineage transfer can land on the SAME revision
        # number with different rows — the revision check alone would
        # keep serving the old lineage's compiled graph
        if hasattr(engine, "_compiled"):
            with engine._lock:
                engine._compiled = None
        log.info("catch-up: installed leader state at revision %d",
                 engine.store.revision)
        return
    effects = meta.get("effects")
    if effects:
        engine.store.apply_effects(effects, int(meta["revision"]))
        log.info("catch-up: applied %d effects to revision %d",
                 len(effects), engine.store.revision)


def follower_loop(engine, leader_host: str, leader_port: int,
                  token: Optional[str] = None,
                  ssl_context=None,
                  server_hostname: Optional[str] = None,
                  from_revision: Optional[int] = None) -> None:
    """Blocking follower: subscribe to the leader's mirror stream and
    replay every action on the local engine — the device dispatches then
    meet the leader's inside the shard_map collectives. Returns when
    the leader closes the connection; raises on protocol errors.
    ``ssl_context`` wraps the subscription in TLS (the leader serves the
    ordinary engine endpoint, which is TLS unless --engine-insecure).

    ``from_revision`` (a restarting follower's own recovered revision —
    ``engine.revision`` after ``enable_persistence``) asks the leader for
    catch-up: the delta since that revision arrives as the stream's first
    frame (effects replay or a full state transfer) before live mirror
    frames, so rejoining needs no manual bulk_load and no unbroken
    process-lifetime stream."""
    import socket
    import struct
    import time as _time

    from ..engine.remote import EngineServer, _pack, _read_frame_sync

    # the leader binds its port AFTER the symmetric jax.distributed
    # startup, so the follower may dial first: retry refusals briefly
    deadline = _time.monotonic() + 120
    while True:
        try:
            s = socket.create_connection((leader_host, leader_port),
                                         timeout=5)
            break
        except OSError:
            if _time.monotonic() > deadline:
                raise MultiHostError(
                    f"leader {leader_host}:{leader_port} never came up")
            _time.sleep(0.25)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if ssl_context is not None:
        try:
            s = ssl_context.wrap_socket(
                s, server_hostname=server_hostname or leader_host)
        except Exception:
            s.close()
            raise
    # heartbeats arrive every PUSH_HEARTBEAT on idle streams; anything
    # slower means a dead leader, not an idle one (a None timeout would
    # leave a partitioned follower blocked forever, invisible to its
    # supervisor)
    s.settimeout(EngineServer.PUSH_HEARTBEAT * 3 + 5.0)
    msg = {"op": "mirror_subscribe"}
    if from_revision is not None:
        msg["from_revision"] = int(from_revision)
    if token:
        msg["token"] = token
    try:
        s.sendall(_pack(msg))
        ack = _read_frame_sync(s)
        if isinstance(ack, tuple) or not ack.get("ok"):
            raise MultiHostError(f"mirror subscribe rejected: {ack}")
        expect = None
        skip_upto = None
        while True:
            frame = _read_frame_sync(s)
            blob = None
            if isinstance(frame, tuple):
                # binary mirror frame: (meta, payload) — the hot
                # check_bulk batches ride a compact payload
                frame, blob = frame
            if not frame.get("ok"):
                raise MultiHostError(f"mirror stream error: {frame}")
            if frame.get("hb"):
                continue  # idle-stream liveness heartbeat
            if "catchup" in frame:
                apply_catchup(engine, frame["catchup"], blob)
                # actions sequenced at or before the cut are inside the
                # catch-up state; queued frames up to it must be skipped
                skip_upto = frame["catchup"].get("seq")
                continue
            payload = frame["frame"]
            # first frame sets the baseline (a leader cannot have served
            # traffic before followers joined — its collectives would
            # have blocked — so nothing real precedes it); after that the
            # stream must be gap-free
            expect = payload["seq"] if expect is None else expect + 1
            if payload["seq"] != expect:
                raise MultiHostError(
                    f"mirror gap: expected seq {expect}, "
                    f"got {payload['seq']}")
            if skip_upto is not None and payload["seq"] <= skip_upto:
                continue  # already covered by the catch-up cut
            apply_mirror_frame(engine, payload, blob)
    except (ConnectionResetError, struct.error):
        return  # leader went away: the process set restarts as a unit
    finally:
        s.close()
