"""Multi-host (DCN) execution of the sharded engine.

The reference's distributed story is NCCL/MPI-free — gRPC between
processes (SURVEY §2.5). The TPU-native analog has two tiers:

- WITHIN a slice: XLA collectives over ICI inside the shard_map'd
  fixpoint (`parallel/sharded.py`) — no host involvement per hop.
- ACROSS hosts: the SAME shard_map over a global mesh spanning every
  process's devices, with XLA routing the collectives over DCN.
  JAX's multi-controller SPMD model requires every process to execute
  the same program on the same inputs; :func:`init_distributed` wires a
  process into the coordination service, and ``make_mesh`` (mesh.py)
  builds over ``jax.devices()`` — the GLOBAL device list — when asked.

`tests/test_multihost.py` validates the full engine query path (bulk
load, dense blocks, collective joins, incremental writes) over two OS
processes with Gloo carrying the cross-process collectives — the CPU
stand-in for DCN.

Serving integration (an engine host whose replicas span hosts) is the
NEXT step, not yet wired: every process must apply the same writes and
execute the same dispatches, so the TCP-serving process would broadcast
(write-ops, query inputs) to follower processes — e.g. via
``jax.experimental.multihost_utils.broadcast_one_to_all`` — before each
step. The collective compute path that loop would execute is exactly
what the validation harness proves out today.
"""

from __future__ import annotations

import jax


class MultiHostError(RuntimeError):
    pass


def init_distributed(spec: str) -> None:
    """Join the JAX distributed coordination service.

    ``spec`` is ``coordinator_host:port,num_processes,process_id`` —
    mirrors ``jax.distributed.initialize``'s required arguments as one
    string. Called today by the multi-host validation harness
    (tests/test_multihost.py); a multi-host serving engine host would
    call it before building its mesh (see the module docstring for the
    remaining serving-integration design)."""
    parts = spec.split(",")
    if len(parts) != 3:
        raise MultiHostError(
            f"--distributed {spec!r}: expected "
            "coordinator_host:port,num_processes,process_id")
    coordinator, num, pid = parts
    try:
        n, p = int(num), int(pid)
    except ValueError:
        raise MultiHostError(
            f"--distributed {spec!r}: num_processes and process_id "
            "must be integers") from None
    if not (0 <= p < n):
        raise MultiHostError(
            f"--distributed {spec!r}: process_id must be in [0, {n})")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n, process_id=p)
