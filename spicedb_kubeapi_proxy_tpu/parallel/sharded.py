"""Sharded slot-space reachability: the multi-chip execution path.

Wraps a :class:`~spicedb_kubeapi_proxy_tpu.ops.reachability.CompiledGraph`
and runs the same fixpoint over a ``("data", "graph")`` mesh:

- the (dst-sorted) residual edge arrays are split into contiguous chunks
  along the ``graph`` axis; every chip gathers/segment-maxes over its chunk
  and the partial propagations are joined with ``lax.pmax`` over ICI — the
  sparse analog of tensor-parallel partial-sum matmuls;
- dense relation blocks ride the MXU *inside* the shard_map body: each
  block's ``A[n_dst, n_src]`` int8 matrix is sharded along the src axis
  (``P(None, "graph")``), every chip contracts its frontier column chunk
  against its A chunk, and the same pmax join ORs the partial products —
  textbook tensor parallelism with (AND, OR) in place of (*, +);
- the query batch (rows of the state tensor) is sharded along the ``data``
  axis — concurrent requests, the reference's goroutine fan-out
  (pkg/authz/check.go:77-93), each chip answering its own requests;
- the convergence test is a collective OR over both axes, fused to run
  every K propagation steps (K-step fused fixpoint, see below) so every
  chip runs the same number of steps while small-diameter graphs stop
  paying one cross-axis collective + host-visible sync per hop;
- conditional grants evaluate ON the mesh: the caveat instance tables and
  compiled VM tapes are replicated across every device (``P()``), the
  per-edge caveat rows are sharded WITH their edge segments, and the
  vectorized caveat VM (caveats/vm.py) runs once per dispatch inside the
  shard_map body — edge activation = expiration ∧ ``cav_ok[row]`` is
  computed where the edges live, so caveated graphs no longer abandon
  the mesh for the single-device path.

K-step fused convergence: the while body applies K propagation steps and
pays ONE convergence collective per block — a pmax of the K per-step
local change flags (one [K] int32 vector, the same collective count as
the old single scalar) — so the collective-OR (and the host-side
while-condition sync it implies) fires ``ceil(iters / K)`` times instead
of ``iters`` times. K derives from the compiled graph's stratification
(:func:`~...ops.reachability.convergence_fuse_steps`): stratified graphs
iterate only their small cyclic core, unstratified ones fuse more. The
iteration is monotone, so steps past the fixpoint are no-ops — fusing
trades at most K-1 wasted cheap hops for the saved collectives — and the
loop carry buffers are donated/double-buffered by the ``while_loop``
lowering (no fresh HBM per block). Because the flags are per step,
``iterations()`` reports the ACTUAL converged-at step (the number of
steps that changed anything — no longer quantized to K, so the engine's
occupancy/crossover telemetry sees true depths); ``conv_checks()``
reports the convergence collectives actually paid.

Propagation itself is one call per level into the masked-semiring
primitive (ops/semiring.propagate) — the SAME primitive the
single-device fixpoint uses, with the ``(exp > now) ∧ cav_ok[row]``
edge-activation mask hoisted to once per dispatch (= once per K-step
fused window) — followed by the pmax partial-product join over ICI. The
join stays OUTSIDE the primitive's push/pull ``lax.cond`` branches:
devices whose data shards diverge on the traced occupancy branch must
never meet a collective inside one branch.

The query surface is both a *grid* (``B`` subjects x ``Q`` result slots
per subject — bulk checks and concurrent list prefilters, BASELINE config
5's shape) and the engine's flat ``query_async(seeds, q_slots, q_batch)``
form, so :class:`~spicedb_kubeapi_proxy_tpu.engine.engine.Engine` can
route every check/lookup through the mesh unchanged (``Engine(mesh=...)``
/ ``--engine-mesh``).

Incremental updates are O(delta) here too: :meth:`ShardedGraph.updated`
reuses the jitted shard_map and the resident base edge shards, applying
only the new dead-pair kills (functional expiration/block-cell updates)
and patching the small sharded delta segment in place — including the
per-slot caveat rows, and new (caveat, context) instance rows appended
into the replicated context tables' spare rows — mirroring the
single-chip incremental path instead of rebuilding and re-placing the
whole graph per write.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops import semiring
from ..ops.reachability import (
    CompiledGraph,
    ConvergenceError,
    DEFAULT_MAX_ITERS,
    LANE,
    _apply_program,
    _next_bucket,
    _seed_base,
    convergence_fuse_steps,
)


def _run_sharded(meta, block_meta, ng: int, level_edges, blocks,
                 dsrc, ddst, dexp, dcav, cav_static, cav_req,
                 seeds, q_slots, now_rel, crossover, *,
                 max_iters: int, k_steps: int):
    """Per-device body (inside shard_map). Shapes are the LOCAL shards:
    level_edges[k] = (src, dst, exp, cav) [E_k/ng] (per stratification
    level, each chunk dst-sorted); blocks[i] [n_dst, n_src/ng];
    dsrc/ddst/dexp/dcav [D/ng] (the incremental delta segment); seeds
    [B/nd, 2]; q_slots [B/nd, Q]. ``cav_static`` (instance tables + VM
    tapes) and ``cav_req`` (request context) are REPLICATED — every chip
    evaluates the same tiny caveat VM pass and masks its own edge shard
    with the resulting validity rows. ``meta`` is a slim RunMeta (not
    the CompiledGraph — the closure must not pin host/device graph
    state).

    Same stratified schedule as the single-chip _run, through the SAME
    masked-semiring primitive (ops/semiring.propagate, with
    ``shard=(g_idx, ng)`` so block frontiers cover only this chip's
    src-axis chunk): only the cyclic core (level 0) iterates; each
    acyclic level is applied once; partial propagations are joined with
    pmax over ICI AFTER the primitive returns (outside its push/pull
    lax.cond — a collective inside a branch devices may disagree on
    would deadlock). Edge activation (expiration ∧ caveat verdict) is
    hoisted to once per dispatch — once per K-step fused window, not
    once per hop. The while body fuses ``k_steps`` propagation steps
    per convergence collective, pmaxing the K per-step change flags as
    one vector so the true converged-at step survives fusing."""
    B = seeds.shape[0]
    rows = meta.M // LANE + 1  # + trash row
    Mp = rows * LANE
    if meta.cav_rows > 1:
        from ..caveats.vm import eval_caveats

        # one VM pass per dispatch (contexts don't change mid-query);
        # replicated inputs => every chip computes identical cav_ok
        cav_ok, cav_missing = eval_caveats(
            meta.caveats, cav_static, cav_req, meta.cav_rows)
    else:
        cav_ok = None
        cav_missing = jnp.int32(0)
    # fused edge activation, computed exactly once per dispatch for the
    # delta overlay and every level's edge shard (this chip's cav-row
    # shard rides with its edges)
    dact = semiring.edge_activation(dexp, now_rel, dcav, cav_ok)
    acts = tuple(
        semiring.edge_activation(exp_rel, now_rel, cav, cav_ok)
        for _, _, exp_rel, cav in level_edges)
    no_bits = tuple(None for _ in block_meta)  # mesh blocks: matmul/Pallas
    brange = jnp.arange(B, dtype=jnp.int32)
    base = _seed_base(meta, seeds)
    baseflat = base.reshape(B, Mp)
    g_idx = jax.lax.axis_index("graph")

    def prop_level(V, k):
        Vflat = V.reshape(B, Mp)
        src, dst = level_edges[k][0], level_edges[k][1]
        occ = semiring.frontier_occupancy(Vflat)
        prop, is_push = semiring.propagate(
            block_meta, blocks, no_bits, src, dst, acts[k],
            dsrc, ddst, dact, Vflat, occ, crossover,
            level=k, mode=meta.spmm_mode, shard=(g_idx, ng))
        # join partials over ICI — outside the primitive's mode cond
        return jax.lax.pmax(prop, "graph"), is_push

    core_progs = [p for p in meta.programs if p.level == 0]

    def step(V):
        prop, is_push = prop_level(V, 0)
        return _apply_program(
            meta, prop.reshape(B, rows, LANE) | base, core_progs), is_push

    def cond(state):
        _, prev_changed, it, _, _ = state
        return (prev_changed > 0) & (it < max_iters)

    def body(state):
        V, _, it, checks, n_push = state
        # K-step fusing: k_steps hops per convergence collective. The
        # fixpoint is monotone, so hops past convergence are no-ops.
        # Each step's LOCAL change flag is recorded and the K flags are
        # joined in ONE pmax (a [K] vector — same collective count as
        # the old scalar): fl[k] == 0 iff step k changed nothing
        # anywhere, so fl.sum() is the number of real steps this block
        # ran (monotonicity makes the flags a 1*0* prefix pattern) and
        # fl[-1] == 0 means the fixpoint is reached. Every chip sees
        # identical fl, so all agree on the loop exit.
        Vk = V
        flags = []
        pushes = jnp.int32(0)
        for _ in range(k_steps):
            V2, is_push = step(Vk)
            flags.append(jnp.any(V2 != Vk).astype(jnp.int32))
            pushes = pushes + is_push
            Vk = V2
        fl = jax.lax.pmax(jnp.stack(flags), ("data", "graph"))
        return (Vk, fl[k_steps - 1], it + fl.sum(), checks + 1,
                n_push + pushes)

    V, still_changing, iters, checks, n_push = jax.lax.while_loop(
        cond, body, (base, jnp.int32(1), jnp.int32(0), jnp.int32(0),
                     jnp.int32(0))
    )
    # acyclic levels: one application each (see ops/reachability._run)
    for k in range(1, meta.n_levels + 1):
        progs_k = [p for p in meta.programs if p.level == k]
        prop, is_push = prop_level(V, k)
        n_push = n_push + is_push
        propb = prop | baseflat
        Vflat = V.reshape(B, Mp)
        for off, size in meta.level_ranges[k - 1]:
            Vflat = jax.lax.dynamic_update_slice(
                Vflat, jax.lax.dynamic_slice(propb, (0, off), (B, size)),
                (0, off))
        V = _apply_program(meta, Vflat.reshape(B, rows, LANE), progs_k)
    out = V.reshape(B, Mp)[brange[:, None], q_slots].astype(jnp.bool_)
    # replicate the (tiny, bool) result over the data axis so it is fully
    # addressable on EVERY process — under a multi-host mesh a
    # data-sharded output cannot be fetched by the serving process
    out = jax.lax.all_gather(out, "data", axis=0, tiled=True)
    # push counts may diverge per data shard (occupancy is local): join
    # so the P() out_spec's replication promise holds
    n_push = jax.lax.pmax(n_push, ("data", "graph"))
    converged = still_changing == 0
    # fl.sum() counts CHANGING hops; the single-chip loop also pays (and
    # reports) exactly one confirming no-op hop when it converges — count
    # it here too so both futures report the same converged-at step
    iters = iters + converged.astype(jnp.int32)
    return out, converged, iters, checks, n_push, cav_missing


class ShardedQueryFuture:
    """A dispatched sharded query (grid or flat form). ``result()`` blocks
    and validates convergence; ``iterations()`` mirrors the single-chip
    QueryFuture so the engine's metrics finalizers work unchanged — it
    reports the ACTUAL converged-at step (per-step change flags survive
    the K-step fuse), so occupancy/crossover telemetry is no longer
    quantized to K; ``conv_checks()`` is the number of convergence
    collectives actually paid; ``push_steps()`` how many core hops took
    the semiring push branch; ``caveats_missing()`` the missing-context
    instance count (fail-closed denials, counted by the engine)."""

    __slots__ = ("_out", "_converged", "_iters", "_sel", "_max_iters",
                 "_cav_missing", "_k_steps", "_checks", "_push")

    def __init__(self, out, converged, iters, sel, max_iters,
                 cav_missing=None, k_steps=1, checks=None, push=None):
        self._out = out
        self._converged = converged
        self._iters = iters
        self._sel = sel  # None (grid) | (rows, cols) flat re-map |
        # ("contig_grid", L, R) row-major window slice
        self._max_iters = max_iters
        self._cav_missing = cav_missing
        self._k_steps = max(int(k_steps), 1)
        self._checks = checks
        self._push = push

    def result(self) -> np.ndarray:
        if not bool(self._converged):
            raise ConvergenceError(
                f"sharded reachability did not converge within "
                f"{self._max_iters} iterations"
            )
        out = np.asarray(self._out)
        if self._sel is None:
            return out
        if isinstance(self._sel[0], str):  # ("contig_grid", L, R)
            # homogeneous fused batch: flat order IS the row-major grid
            _, L, R = self._sel
            return out[:R, :L].reshape(-1)
        rows, cols = self._sel
        return out[rows, cols]

    def iterations(self) -> int:
        return int(self._iters)

    def conv_checks(self) -> int:
        """Convergence collective-ORs this query paid: one per K-step
        block, vs one per hop before fusing. Counted directly by the
        traced loop (``iterations()`` now reports true steps, so the
        old ``iters / K`` reconstruction would under-count blocks whose
        tail steps were no-ops)."""
        if self._checks is not None:
            return int(self._checks)
        return -(-int(self._iters) // self._k_steps)

    def push_steps(self) -> int:
        return 0 if self._push is None else int(self._push)

    def caveats_missing(self) -> int:
        return 0 if self._cav_missing is None else int(self._cav_missing)


def _pair_keys(pairs: Optional[np.ndarray]) -> np.ndarray:
    if pairs is None or not len(pairs):
        return np.empty(0, dtype=np.int64)
    return pairs[:, 0].astype(np.int64) * (1 << 32) + pairs[:, 1]


class ShardedGraph:
    """A CompiledGraph pinned across a device mesh.

    Edge tensors and dense-block matrices are placed once with ``graph``-
    axis shardings and stay device-resident across queries; the caveat
    instance tables + VM tapes are replicated across every device; only
    seeds/queries, the (tiny) per-request caveat context, and — after
    incremental writes — the small delta/instance patches move
    host->device.

    Tiered storage scope (storage/): the mesh backend keeps EVERY block
    resident — per-dispatch demand streaming is a single-chip-path
    feature (the shard_map's operand tuple is fixed at build time). A
    tiered graph whose blocks fit the budget builds here normally and
    simply accounts all blocks hot (``TierStore.mark_sharded``); one
    that exceeds the budget never reaches this class — Engine._backend
    routes it to the single-chip streaming path and counts the decision
    in ``engine_tier_mesh_fallback_total``.
    """

    def __init__(self, cg: CompiledGraph, mesh: Mesh,
                 max_iters: int = DEFAULT_MAX_ITERS,
                 k_steps: Optional[int] = None):
        reason = self.unsupported_reason(cg)
        if reason is not None:
            # serving such a graph here would FAIL OPEN (conditional
            # edges with no per-edge rows to mask). Engine._backend
            # routes these through the single-device path; refusing
            # here keeps any other caller honest.
            raise ValueError(f"ShardedGraph cannot serve this graph: "
                             f"{reason}")
        self.cg = cg
        self.mesh = mesh
        self.max_iters = max_iters
        self.nd = mesh.shape["data"]
        self.ng = mesh.shape["graph"]
        self._edge_sh = NamedSharding(mesh, P("graph"))
        self._block_sh = NamedSharding(mesh, P(None, "graph"))
        self._repl_sh = NamedSharding(mesh, P())

        meta = cg.run_meta()
        # the raw override (None = derive per graph) is kept so updated()'s
        # full-rebuild paths preserve an explicit caller choice instead of
        # silently reverting to the derived default mid-stream
        self._k_override = k_steps
        self.k_steps = (max(int(k_steps), 1) if k_steps
                        else convergence_fuse_steps(meta))

        # the overlay host arrays (delta segment, res_exp, dead ledger,
        # caveat instance tables) are SHARED and mutated in place by
        # incremental_update — read them under the graph's host guard so
        # a racing overlay append cannot tear the snapshot this build
        # uploads
        with cg._host_guard():
            level_arrays, kept = self._host_level_edges()
            # host copies for the incremental dead-pair search (per
            # level, each dst-sorted)
            self._h_levels = level_arrays
            self._level_edges = tuple(
                tuple(jax.device_put(a, self._edge_sh) for a in quad)
                for quad in level_arrays
            )
            self._block_meta = tuple(kept)
            self._blocks = tuple(
                jax.device_put(self._block_matrix(bm), self._block_sh)
                for bm in kept
            )
            (self._dsrc, self._ddst, self._dexp, self._dcav,
             self._h_dexp, self._h_dcav) = self._delta_device(cg)
            # caveat instance tables + tapes: replicated on every device
            # (tiny next to the edge shards), plus the per-caveat
            # applied-row watermark updated() syncs spare-row appends
            # against
            cavt = cg.caveats
            if cavt is not None and cavt.metas:
                self._cav_static = cavt.device_static(
                    sharding=self._repl_sh)
                self._applied_inst = cavt.applied_rows()
            else:
                self._cav_static = ()
                self._applied_inst = ()
        if cg.tier is not None:
            # mesh placement: every materialized block is device-resident
            # for the life of this build — account it hot so the
            # occupancy gauges tell the truth under a mesh too (outside
            # the host guard: the tier store has its own lock)
            idxs = [cg.block_index.get((bm.dst_off, bm.src_off))
                    for bm in self._block_meta]
            cg.tier.mark_sharded([i for i in idxs if i is not None])
            cg.tier.publish_gauges()
        # dead pairs already folded into this build (updated() applies
        # only the new tail); _applied_delta / _h_dexp / _h_dcav let
        # updated() patch only the overlay slots that actually changed
        # instead of re-uploading the whole segment per write
        self._applied_dead = _pair_keys(cg.dead_pairs)
        self._applied_delta = cg.n_delta
        # device query-grid cache for layout-pure queries (shared across
        # updated() generations: the slot layout is incremental-invariant)
        self._qgrid: dict = {}

        if meta.n_levels + 1 != len(self._level_edges):
            raise AssertionError(
                "level edge arrays out of step with stratification")
        fn = partial(_run_sharded, meta, self._block_meta, self.ng,
                     max_iters=max_iters, k_steps=self.k_steps)
        smap_kw = dict(
            mesh=mesh,
            in_specs=(
                tuple((P("graph"),) * 4 for _ in self._level_edges),
                tuple(P(None, "graph") for _ in kept),
                P("graph"), P("graph"), P("graph"), P("graph"),
                P(), P(),
                P("data", None), P("data", None), P(), P(),
            ),
            out_specs=(P(None, None), P(), P(), P(), P(), P()),
        )
        try:
            smapped = shard_map(fn, check_vma=False, **smap_kw)
        except TypeError:
            # older jax spells the replication-check toggle check_rep —
            # and its default (True) has no replication rule for
            # while_loop, so it must be disabled, not defaulted
            smapped = shard_map(fn, check_rep=False, **smap_kw)
        self._run = jax.jit(smapped)

    @staticmethod
    def unsupported_reason(cg: CompiledGraph) -> Optional[str]:
        """Why this graph cannot run on the mesh, or ``None`` (the
        common case — caveated graphs ARE served here). The one
        genuinely unsupported shape: a caveated graph without complete
        stratified per-edge caveat rows (hand-built layouts) — its
        level arrays would carry no rows to mask, so conditional edges
        would serve unconditionally (fail open). The predicate MIRRORS
        the branches ``_host_level_edges`` actually takes: the
        ``res_idx is None or res_src is None`` whole-edge-set path
        builds zero cav rows, and a ``res_cav``/``res_src`` length
        mismatch would zero-fill — both must refuse when caveat
        instances exist (compiled graphs always set all three
        together). Engine._backend counts these in
        ``engine_caveat_mesh_fallback_total`` and keeps them on the
        single-device path."""
        cavt = getattr(cg, "caveats", None)
        if cavt is not None and getattr(cavt, "metas", ()):
            if cg.res_idx is None or cg.res_src is None \
                    or cg.res_cav is None \
                    or len(cg.res_cav) != len(cg.res_src):
                return ("caveated graph without per-edge caveat rows "
                        "(unstratified/hand-built layout)")
        return None

    # -- host-side construction ---------------------------------------------

    def _dead_set(self):
        if self.cg.dead_pairs is None or not len(self.cg.dead_pairs):
            return None
        d = self.cg.dead_pairs
        return set(zip(d[:, 0].tolist(), d[:, 1].tolist()))

    def _pad_level(self, src, dst, exp, cav):
        """Pad one level's edges with trash rows so the graph axis
        divides evenly (at least ng rows so every chip has a chunk)."""
        n = max(len(src), 1)
        n_pad = ((n + self.ng - 1) // self.ng) * self.ng
        s = np.full(n_pad, self.cg.M, dtype=np.int32)
        d = np.full(n_pad, self.cg.M, dtype=np.int32)
        e = np.full(n_pad, -np.inf, dtype=np.float32)
        c = np.zeros(n_pad, dtype=np.int32)  # pad rows: uncaveated
        s[: len(src)] = src
        d[: len(dst)] = dst
        e[: len(exp)] = exp
        c[: len(cav)] = cav
        return s, d, e, c

    def _host_level_edges(self):
        """(level_arrays, kept_blocks): per stratification level 0..L, the
        (src, dst, exp, cav) edge arrays this mesh gathers over (base
        residual slice + folded-back blocks of that level, dst-sorted,
        padded to the graph axis) and the dense blocks that stay on the
        MXU path (src axis divisible by the graph-axis size). Folded
        block edges are never caveated (caveated edges are excluded from
        dense blocks at compile, like expiring ones), so they carry
        row 0."""
        cg = self.cg
        dead = self._dead_set()
        if cg.res_idx is None or cg.res_src is None:
            # no dense split computed: whole edge set on the segment path
            # as one core level, with dead pairs killed in place
            # (unsupported_reason refuses caveated graphs in this shape,
            # so the cav rows are all 0)
            b_src = cg.src[: cg.n_edges].astype(np.int32, copy=False)
            b_dst = cg.dst[: cg.n_edges].astype(np.int32, copy=False)
            b_exp = cg.exp_rel[: cg.n_edges].astype(np.float32, copy=True)
            b_cav = np.zeros(cg.n_edges, dtype=np.int32)
            if dead:
                for s, t in dead:
                    lo = int(np.searchsorted(b_dst, t, side="left"))
                    hi = int(np.searchsorted(b_dst, t, side="right"))
                    if lo < hi:
                        hit = lo + np.flatnonzero(b_src[lo:hi] == s)
                        b_exp[hit] = -np.inf
            return [self._pad_level(b_src, b_dst, b_exp, b_cav)], []
        kept, folded = [], []
        for bm in cg.blocks:
            if bm.n_src % self.ng == 0:
                kept.append(bm)
            else:
                folded.append(bm)
        bounds = cg.res_level_bounds or (0, len(cg.res_src))
        res_cav = cg.res_cav
        if res_cav is None or len(res_cav) != len(cg.res_src):
            res_cav = np.zeros(len(cg.res_src), dtype=np.int32)
        n_levels = cg.n_levels
        out = []
        for k in range(n_levels + 1):
            # base residual slice for the level: already dst-sorted and
            # carrying incremental invalidations (res_exp -> -inf); its
            # trailing bucket padding is harmless trash
            lo, hi = bounds[k], bounds[k + 1]
            parts = [(cg.res_src[lo:hi], cg.res_dst[lo:hi],
                      cg.res_exp[lo:hi], res_cav[lo:hi])]
            for bm in folded:
                if bm.level != k:
                    continue
                e_src = (bm.src_off + bm.src_local).astype(np.int32)
                e_dst = (bm.dst_off + bm.dst_local).astype(np.int32)
                keep = self._not_dead_mask(e_src, e_dst, dead)
                n_keep = int(keep.sum())
                parts.append((
                    e_src[keep], e_dst[keep],
                    np.full(n_keep, np.inf, dtype=np.float32),
                    np.zeros(n_keep, dtype=np.int32)))
            src = np.concatenate([p[0] for p in parts])
            dst = np.concatenate([p[1] for p in parts])
            exp = np.concatenate([p[2] for p in parts])
            cav = np.concatenate([p[3] for p in parts])
            if len(parts) > 1:  # merged folded edges: restore dst order
                order = np.argsort(dst, kind="stable")
                src, dst, exp, cav = (src[order], dst[order], exp[order],
                                      cav[order])
            out.append(self._pad_level(src, dst, exp, cav))
        return out, kept

    @staticmethod
    def _not_dead_mask(e_src, e_dst, dead):
        if not dead:
            return np.ones(len(e_src), dtype=bool)
        return np.fromiter(
            ((s, t) not in dead for s, t in zip(e_src.tolist(),
                                                e_dst.tolist())),
            dtype=bool, count=len(e_src))

    def _block_matrix(self, bm) -> np.ndarray:
        A = np.zeros((bm.n_dst, bm.n_src), dtype=np.int8)
        A[bm.dst_local, bm.src_local] = 1
        dl, sl = self.cg._dead_cells(bm)
        if len(dl):
            A[dl, sl] = 0
        return A

    def _delta_device(self, cg: CompiledGraph):
        """Upload the delta segment, padded so the graph axis divides.
        Returns the four device arrays plus the padded host expiration
        and caveat-row copies — updated()'s change-detection mirrors."""
        d_src, d_dst, d_exp, d_cav = cg._delta_host()
        pad = len(d_src)
        if pad % self.ng:
            pad2 = ((pad + self.ng - 1) // self.ng) * self.ng
            d_src = np.concatenate(
                [d_src, np.full(pad2 - pad, cg.M, dtype=np.int32)])
            d_dst = np.concatenate(
                [d_dst, np.full(pad2 - pad, cg.M, dtype=np.int32)])
            d_exp = np.concatenate(
                [d_exp, np.full(pad2 - pad, -np.inf, dtype=np.float32)])
            d_cav = np.concatenate(
                [d_cav, np.zeros(pad2 - pad, dtype=np.int32)])
        return (jax.device_put(d_src, self._edge_sh),
                jax.device_put(d_dst, self._edge_sh),
                jax.device_put(d_exp, self._edge_sh),
                jax.device_put(d_cav, self._edge_sh),
                np.array(d_exp, dtype=np.float32),
                np.array(d_cav, dtype=np.int32))

    # -- incremental updates -------------------------------------------------

    def updated(self, cg: CompiledGraph) -> "ShardedGraph":
        """A ShardedGraph for an incrementally-updated revision of the same
        compiled graph, reusing the jitted shard_map and resident base
        shards; falls back to a full rebuild when the shape changed (delta
        bucket growth, different blocks, full recompile)."""
        old = self.cg
        if cg is old:
            return self

        def rebuild() -> "ShardedGraph":
            # ONE spelling of the full-rebuild fallback: every early
            # return must carry the same construction-time preferences
            # (an explicit k_steps override must survive a rebuild)
            return ShardedGraph(cg, self.mesh, self.max_iters,
                                self._k_override)

        if cg.signature() != old.signature():
            return rebuild()
        # signature equality only proves JIT compatibility (shapes,
        # layout, stratification) — delta-apply is valid ONLY for
        # incremental descendants, which share their base edge arrays BY
        # OBJECT (incremental_update builds the new graph with
        # res_src=cg.res_src). A FULL recompile can coincidentally keep
        # the signature (bucket padding absorbs small edge-count changes)
        # while folding the delta into NEW base arrays — the resident
        # shards would then silently miss those edges and answer stale
        # denials.
        if not (cg.res_src is old.res_src and cg.res_dst is old.res_dst
                and cg.src is old.src and cg.dst is old.dst):
            return rebuild()
        reclosed_idx: list[int] = []
        if cg.blocks is not old.blocks:
            # a re-closed closured block (incremental membership delete)
            # keeps shape/level/flags — only its cells changed. Re-upload
            # just those matrices instead of rebuilding the whole sharded
            # state; anything else (and folded blocks, whose closure
            # edges live inside the level arrays) needs the full rebuild.
            if len(cg.blocks) != len(old.blocks):
                return rebuild()
            for i, (nb, ob) in enumerate(zip(cg.blocks, old.blocks)):
                if nb is ob:
                    continue
                same_shape = (
                    nb.dst_off == ob.dst_off and nb.n_dst == ob.n_dst
                    and nb.src_off == ob.src_off and nb.n_src == ob.n_src
                    and nb.level == ob.level and nb.closured
                    and ob.closured)
                if not same_shape or nb.n_src % self.ng:
                    return rebuild()
                reclosed_idx.append(i)
        new = object.__new__(ShardedGraph)
        new.__dict__.update(self.__dict__)
        new.cg = cg
        if reclosed_idx:
            kept_pos = {}
            pos = 0
            for i, bm in enumerate(cg.blocks):
                if bm.n_src % self.ng == 0:
                    kept_pos[i] = pos
                    pos += 1
            blocks = list(new._blocks)
            for i in reclosed_idx:
                blocks[kept_pos[i]] = jax.device_put(
                    self._block_matrix(cg.blocks[i]), self._block_sh)
            new._blocks = tuple(blocks)
        # kill base edges for dead pairs not yet applied to these shards
        keys = _pair_keys(cg.dead_pairs)
        fresh = keys[~np.isin(keys, self._applied_dead)]
        if len(fresh):
            pairs = np.stack([fresh >> 32, fresh & ((1 << 32) - 1)], axis=1)
            pos_per_level: dict[int, list] = {}
            block_cells: dict[int, list] = {}
            for s, t in pairs.tolist():
                for k, (h_src, h_dst, _, _) in enumerate(self._h_levels):
                    lo = int(np.searchsorted(h_dst, t, side="left"))
                    hi = int(np.searchsorted(h_dst, t, side="right"))
                    if lo < hi:
                        pos_per_level.setdefault(k, []).extend(
                            (lo + np.flatnonzero(
                                h_src[lo:hi] == s)).tolist())
                for i, bm in enumerate(self._block_meta):
                    if (bm.dst_off <= t < bm.dst_off + bm.n_dst
                            and bm.src_off <= s < bm.src_off + bm.n_src):
                        block_cells.setdefault(i, []).append(
                            (t - bm.dst_off, s - bm.src_off))
            if pos_per_level:
                levels = list(self._level_edges)
                for k, pos in pos_per_level.items():
                    s_dev, d_dev, e_dev, c_dev = levels[k]
                    levels[k] = (s_dev, d_dev, jax.device_put(
                        e_dev.at[np.asarray(pos, dtype=np.int64)]
                        .set(-np.inf), self._edge_sh), c_dev)
                new._level_edges = tuple(levels)
            if block_cells:
                blocks = list(self._blocks)
                for i, cells in block_cells.items():
                    dl = np.asarray([c[0] for c in cells], dtype=np.int32)
                    sl = np.asarray([c[1] for c in cells], dtype=np.int32)
                    blocks[i] = jax.device_put(
                        blocks[i].at[dl, sl].set(0), self._block_sh)
                new._blocks = tuple(blocks)
        new._applied_dead = keys
        with cg._host_guard():
            # overlay: patch ONLY the slots that changed since this
            # sharded view last synced, with functional updates on the
            # device-RESIDENT per-shard copies — an O(write) scatter
            # instead of re-uploading the whole capacity-sized segment
            # on every write (the pre-patch behavior, which made each
            # mesh write pay O(capacity) host->device traffic).
            d_src, d_dst, d_exp, d_cav = cg._delta_host()
            n = len(d_exp)
            mirror = self._h_dexp
            mirror_c = self._h_dcav
            # appended slots (src/dst assigned once, at append)...
            app = np.arange(self._applied_delta,
                            min(cg.n_delta, n), dtype=np.int64)
            # ...plus expiration re-touches of EXISTING slots
            # (touch/delete reuse their pair's slot in place)
            changed = np.flatnonzero(mirror[:n] != d_exp)
            changed = np.union1d(changed, app)
            # ...and caveat-row re-touches (a touch may attach, replace,
            # or strip the condition without moving the expiration)
            changed_c = np.union1d(
                np.flatnonzero(mirror_c[:n] != d_cav), app)
            if len(changed):
                new._h_dexp = mirror.copy()
                new._h_dexp[changed] = d_exp[changed]
                if len(app):
                    new._dsrc = jax.device_put(
                        self._dsrc.at[app].set(d_src[app]),
                        self._edge_sh)
                    new._ddst = jax.device_put(
                        self._ddst.at[app].set(d_dst[app]),
                        self._edge_sh)
                new._dexp = jax.device_put(
                    self._dexp.at[changed].set(d_exp[changed]),
                    self._edge_sh)
            if len(changed_c):
                new._h_dcav = mirror_c.copy()
                new._h_dcav[changed_c] = d_cav[changed_c]
                new._dcav = jax.device_put(
                    self._dcav.at[changed_c].set(d_cav[changed_c]),
                    self._edge_sh)
            new._applied_delta = cg.n_delta
            # caveat instance appends: incremental_update placed new
            # (caveat, context) rows into the shared host tables' spare
            # rows (append-only per caveat) — patch exactly those column
            # ranges into the REPLICATED device tables, O(new rows)
            cavt = cg.caveats
            if cavt is not None and cavt.metas and self._cav_static:
                used = cavt.applied_rows()
                if used != self._applied_inst:
                    cs = list(self._cav_static)
                    for ci, (lo, hi) in enumerate(
                            zip(self._applied_inst, used)):
                        if hi <= lo:
                            continue
                        h = cavt.hosts[ci]
                        sl = slice(lo, hi)
                        ent = dict(cs[ci])
                        ent["ce"] = ent["ce"].at[:, sl].set(h.ctx_e[:, sl])
                        ent["cv"] = ent["cv"].at[:, sl].set(h.ctx_v[:, sl])
                        ent["ck"] = ent["ck"].at[:, sl].set(h.ctx_k[:, sl])
                        ent["loe"] = ent["loe"].at[:, :, sl].set(
                            h.lo_e[:, :, sl])
                        ent["lov"] = ent["lov"].at[:, :, sl].set(
                            h.lo_v[:, :, sl])
                        ent["hie"] = ent["hie"].at[:, :, sl].set(
                            h.hi_e[:, :, sl])
                        ent["hiv"] = ent["hiv"].at[:, :, sl].set(
                            h.hi_v[:, :, sl])
                        ent["lk"] = ent["lk"].at[:, sl].set(
                            h.list_k[:, sl])
                        ent["real"] = ent["real"].at[sl].set(h.real[sl])
                        # re-pin the replicated placement explicitly: the
                        # functional update must not leave a table with a
                        # committed single-device layout
                        cs[ci] = {k2: jax.device_put(v2, self._repl_sh)
                                  for k2, v2 in ent.items()}
                    new._cav_static = tuple(cs)
                    new._applied_inst = used
        return new

    # -- dispatch -----------------------------------------------------------

    def _dispatch(self, seeds_pad: np.ndarray, grid: np.ndarray,
                  now_abs: float, cav_req: tuple):
        now_rel = np.float32(now_abs - self.cg.base_time)
        # host numpy inputs stay UNCOMMITTED: jit shards them per the
        # in_specs, which works identically whether the mesh spans one
        # process or many (a committed local array would need a reshard
        # from a non-global placement under multi-controller)
        crossover = np.float32(getattr(self.cg, "spmm_crossover", 1.0))
        out, converged, iters, checks, n_push, cav_missing = self._run(
            self._level_edges, self._blocks,
            self._dsrc, self._ddst, self._dexp, self._dcav,
            self._cav_static, cav_req,
            seeds_pad, grid, now_rel, crossover,
        )
        try:
            out.copy_to_host_async()
            converged.copy_to_host_async()
            iters.copy_to_host_async()
            checks.copy_to_host_async()
            n_push.copy_to_host_async()
            cav_missing.copy_to_host_async()
        except AttributeError:  # non-jax backends in tests
            pass
        return out, converged, iters, checks, n_push, cav_missing

    def _request_arrays(self, context: Optional[dict],
                        cav_req: Optional[tuple], now_abs: float) -> tuple:
        """The per-caveat request-context arrays riding this dispatch
        (replicated); pre-encoded ``cav_req`` (chunked bulk callers)
        wins, else encode here — including the auto-injected ``now``."""
        cavt = self.cg.caveats
        if cavt is None or not cavt.metas:
            return ()
        if cav_req is not None:
            return cav_req
        req, _ = cavt.encode_request(context, now_abs)
        return req

    def _pad_rows(self, B: int) -> int:
        B_pad = max(_next_bucket(B, 1), self.nd)
        if B_pad % self.nd:
            B_pad = ((B_pad + self.nd - 1) // self.nd) * self.nd
        return B_pad

    def query_grid(
        self,
        seed_slots: np.ndarray,  # int32 [B, 2] (subject slot, wildcard slot)
        q_slots: np.ndarray,  # int32 [B, Q] result slots per subject
        now: Optional[float] = None,
        context: Optional[dict] = None,
    ) -> np.ndarray:
        """Run the sharded fixpoint; returns bool [B, Q]."""
        cg = self.cg
        B, Q = q_slots.shape
        B_pad = self._pad_rows(B)
        Q_pad = _next_bucket(Q, 8)
        seeds = np.full((B_pad, 2), cg.M, dtype=np.int32)
        seeds[:B] = seed_slots
        qs = np.full((B_pad, Q_pad), cg.M, dtype=np.int32)
        qs[:B, :Q] = q_slots
        now_abs = time.time() if now is None else now
        out, converged, iters, checks, n_push, cav_missing = self._dispatch(
            seeds, qs, now_abs, self._request_arrays(context, None, now_abs))
        fut = ShardedQueryFuture(out, converged, iters, None,
                                 self.max_iters, cav_missing, self.k_steps,
                                 checks=checks, push=n_push)
        return fut.result()[:B, :Q]

    def query_async(
        self,
        seed_slots: np.ndarray,  # int32 [B, 2]
        q_slots: np.ndarray,  # int32 [Q] flat result slots
        q_batch: np.ndarray,  # int32 [Q] batch row per query
        now: Optional[float] = None,
        q_cache_key: Optional[tuple] = None,
        q_contiguous: Optional[bool] = None,  # accepted for surface parity
        q_contig_grid: Optional[tuple] = None,  # (lo, L, R) promise: R rows
        # x one shared [lo, lo+L) window — skips the rank re-map entirely
        context: Optional[dict] = None,  # request caveat context: merged
        # under the tuple contexts ON the mesh (replicated request
        # arrays), exactly like the single-device dispatch
        cav_req: Optional[tuple] = None,  # pre-encoded request arrays
        # (CompiledCaveats.encode_request) — chunked bulk callers encode
        # ONCE for the whole logical call instead of per chunk
    ) -> ShardedQueryFuture:
        """Engine-compatible flat form (CompiledGraph.query_async surface):
        the flat (q_slots, q_batch) queries are packed into a [B, Qmax]
        grid (rank within row computed vectorized), dispatched, and the
        future re-maps the grid output back to flat [Q] order. The
        iteration budget is the construction-time ``max_iters`` (baked
        into the jitted shard_map). Homogeneous fused batches
        (``q_contig_grid``, engine/batcher.py) bypass the O(Q log Q)
        rank computation and the O(Q) fancy-index result re-map: their
        grid rows are the window itself and the row-major grid slice IS
        the flat order."""
        cg = self.cg
        B = seed_slots.shape[0]
        q_slots = np.asarray(q_slots, dtype=np.int32)
        q_batch = np.asarray(q_batch, dtype=np.int32)
        Q = len(q_slots)
        if (q_contig_grid is None and q_contiguous and Q and B == 1
                and not q_batch[0]):
            # the engine's single-window promise is the R=1 grid
            q_contig_grid = (int(q_slots[0]), Q, 1)
        contig = None
        if q_contig_grid is not None:
            lo, L, R = q_contig_grid
            if Q == L * R and 0 < L and 0 < R <= B and lo + L <= cg.M:
                contig = (lo, L, R)
        if contig is not None:
            lo, L, R = contig
            cols = None
            Qmax = L
        elif Q:
            # rank of each query within its batch row (stable)
            order = np.argsort(q_batch, kind="stable")
            sorted_qb = q_batch[order]
            starts = np.flatnonzero(
                np.r_[True, np.diff(sorted_qb) != 0])
            run_len = np.diff(np.r_[starts, Q])
            grp_start = np.repeat(starts, run_len)
            rank_sorted = np.arange(Q) - grp_start
            cols = np.empty(Q, dtype=np.int64)
            cols[order] = rank_sorted
            Qmax = int(rank_sorted.max()) + 1
        else:
            cols = np.empty(0, dtype=np.int64)
            Qmax = 1
        B_pad = self._pad_rows(B)
        Q_pad = _next_bucket(Qmax, 8)
        seeds = np.full((B_pad, 2), cg.M, dtype=np.int32)
        seeds[:B] = seed_slots
        grid = self._qgrid.get((q_cache_key, B_pad)) \
            if q_cache_key else None
        if grid is None:
            grid_np = np.full((B_pad, Q_pad), cg.M, dtype=np.int32)
            if contig is not None:
                grid_np[:R, :L] = lo + np.arange(L, dtype=np.int32)
            else:
                grid_np[q_batch, cols] = q_slots
            # a GLOBAL device array (not a process-local jnp.asarray):
            # identical on every process, sharded over the data axis —
            # valid on single-process and multi-host meshes alike
            grid = jax.device_put(
                grid_np, NamedSharding(self.mesh, P("data", None)))
            if q_cache_key:
                # bounded: grids pin device memory per distinct key
                if len(self._qgrid) >= 32:
                    self._qgrid.pop(next(iter(self._qgrid)), None)
                self._qgrid[(q_cache_key, B_pad)] = grid
        now_abs = time.time() if now is None else now
        out, converged, iters, checks, n_push, cav_missing = self._dispatch(
            seeds, grid, now_abs,
            self._request_arrays(context, cav_req, now_abs))
        sel = (("contig_grid", L, R) if contig is not None
               else (q_batch, cols))
        return ShardedQueryFuture(out, converged, iters, sel,
                                  max_iters=self.max_iters,
                                  cav_missing=cav_missing,
                                  k_steps=self.k_steps,
                                  checks=checks, push=n_push)
