"""Sharded slot-space reachability: the multi-chip execution path.

Wraps a :class:`~spicedb_kubeapi_proxy_tpu.ops.reachability.CompiledGraph`
and runs the same fixpoint over a ``("data", "graph")`` mesh:

- the (dst-sorted) edge arrays are split into contiguous chunks along the
  ``graph`` axis; every chip gathers/segment-maxes over its chunk and the
  partial propagations are joined with ``lax.pmax`` over ICI — the sparse
  analog of tensor-parallel partial-sum matmuls;
- the query batch (rows of the state tensor ``V[M+1, B]``) is sharded along
  the ``data`` axis — concurrent requests, the reference's goroutine fan-out
  (pkg/authz/check.go:77-93), each chip answering its own requests;
- the convergence test is a collective OR over both axes so every chip runs
  the same number of fixpoint steps.

The query surface is a *grid*: ``B`` subjects × ``Q`` result slots per
subject, which covers both bulk checks (Q = checks per subject) and
concurrent list prefilters (Q = the resource type's object space, one row
per request) — BASELINE config 5's shape.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops.reachability import (
    CompiledGraph,
    ConvergenceError,
    DEFAULT_MAX_ITERS,
    LANE,
    _apply_program,
    _next_bucket,
    _seed_base,
)


def _run_sharded(cg: CompiledGraph, src, dst, exp_rel, seeds, q_slots,
                 now_rel, *, max_iters: int):
    """Per-device body (inside shard_map). Shapes are the LOCAL shards:
    src/dst/exp_rel [E/ng]; seeds [B/nd, 2]; q_slots [B/nd, Q]. State
    layout matches the single-chip fixpoint: [B, rows, LANE] with the
    slot space on the lane axis."""
    B = seeds.shape[0]
    rows = cg.M // LANE + 1  # + trash row
    Mp = rows * LANE
    valid = (exp_rel > now_rel).astype(jnp.uint8)
    brange = jnp.arange(B, dtype=jnp.int32)
    base = _seed_base(cg, seeds)

    def step(V):
        Vflat = V.reshape(B, Mp)
        gathered = (Vflat[:, src] & valid[None, :]).T  # [E_local, B]
        # edges are dst-sorted globally, so each contiguous chunk is sorted
        prop = jax.ops.segment_max(
            gathered, dst, num_segments=Mp, indices_are_sorted=True
        ).T  # [B, Mp]
        prop = jax.lax.pmax(prop, "graph")  # join edge shards over ICI
        return _apply_program(cg, prop.reshape(B, rows, LANE) | base)

    def cond(state):
        _, prev_changed, it = state
        return (prev_changed > 0) & (it < max_iters)

    def body(state):
        V, _, it = state
        V2 = step(V)
        # every chip must agree on the iteration count: OR over both axes
        changed = jnp.any(V2 != V).astype(jnp.int32)
        changed = jax.lax.pmax(changed, ("data", "graph"))
        return V2, changed, it + 1

    V, still_changing, _ = jax.lax.while_loop(
        cond, body, (base, jnp.int32(1), 0)
    )
    out = V.reshape(B, Mp)[brange[:, None], q_slots].astype(jnp.bool_)
    return out, (still_changing == 0)


class ShardedGraph:
    """A CompiledGraph pinned across a device mesh.

    Edge tensors are placed once with a ``P("graph")`` sharding and stay
    device-resident across queries; only seeds/queries move host→device
    per call.
    """

    def __init__(self, cg: CompiledGraph, mesh: Mesh,
                 max_iters: int = DEFAULT_MAX_ITERS):
        self.cg = cg
        self.mesh = mesh
        self.max_iters = max_iters
        self.nd = mesh.shape["data"]
        self.ng = mesh.shape["graph"]

        # fold incremental-update state into the base edge set: dead base
        # edges are invalidated (expiration -> -inf; the query-time mask
        # drops them, row order untouched), delta edges are merged in and
        # the whole set re-sorted by dst (each contiguous chunk must stay
        # sorted for the per-shard segment_max)
        b_src = cg.src[: cg.n_edges].astype(np.int32, copy=False)
        b_dst = cg.dst[: cg.n_edges].astype(np.int32, copy=False)
        b_exp = cg.exp_rel[: cg.n_edges].astype(np.float32, copy=True)
        if cg.dead_pairs is not None and len(cg.dead_pairs):
            for s, t in cg.dead_pairs.tolist():
                lo = int(np.searchsorted(b_dst, t, side="left"))
                hi = int(np.searchsorted(b_dst, t, side="right"))
                if lo < hi:
                    hit = lo + np.flatnonzero(b_src[lo:hi] == s)
                    b_exp[hit] = -np.inf
        if cg.n_delta:
            b_src = np.concatenate([b_src, cg.delta_src[: cg.n_delta]])
            b_dst = np.concatenate([b_dst, cg.delta_dst[: cg.n_delta]])
            b_exp = np.concatenate([b_exp, cg.delta_exp[: cg.n_delta]])
            order = np.argsort(b_dst, kind="stable")
            b_src, b_dst, b_exp = b_src[order], b_dst[order], b_exp[order]

        E_pad = max(len(cg.src), len(b_src))
        if E_pad % self.ng:
            # re-pad with trash edges so the graph axis divides evenly
            E_pad = ((E_pad + self.ng - 1) // self.ng) * self.ng
        src = np.full(E_pad, cg.M, dtype=np.int32)
        dst = np.full(E_pad, cg.M, dtype=np.int32)
        exp = np.full(E_pad, -np.inf, dtype=np.float32)
        src[: len(b_src)] = b_src
        dst[: len(b_dst)] = b_dst
        exp[: len(b_exp)] = b_exp

        edge_sh = NamedSharding(mesh, P("graph"))
        self._src = jax.device_put(src, edge_sh)
        self._dst = jax.device_put(dst, edge_sh)
        self._exp = jax.device_put(exp, edge_sh)

        fn = partial(_run_sharded, cg, max_iters=max_iters)
        self._run = jax.jit(
            shard_map(
                fn,
                mesh=mesh,
                in_specs=(P("graph"), P("graph"), P("graph"),
                          P("data", None), P("data", None), P()),
                out_specs=(P("data", None), P()),
                check_vma=False,
            )
        )

    def query_grid(
        self,
        seed_slots: np.ndarray,  # int32 [B, 2] (subject slot, wildcard slot)
        q_slots: np.ndarray,  # int32 [B, Q] result slots per subject
        now: Optional[float] = None,
    ) -> np.ndarray:
        """Run the sharded fixpoint; returns bool [B, Q]."""
        cg = self.cg
        B, Q = q_slots.shape
        # B must split evenly over the data axis; Q is bucket-padded
        B_pad = max(_next_bucket(B, 1), self.nd)
        if B_pad % self.nd:
            B_pad = ((B_pad + self.nd - 1) // self.nd) * self.nd
        Q_pad = _next_bucket(Q, 8)
        seeds = np.full((B_pad, 2), cg.M, dtype=np.int32)
        seeds[:B] = seed_slots
        qs = np.full((B_pad, Q_pad), cg.M, dtype=np.int32)
        qs[:B, :Q] = q_slots
        now_rel = np.float32(
            (time.time() if now is None else now) - cg.base_time
        )
        out, converged = self._run(
            self._src, self._dst, self._exp,
            jnp.asarray(seeds), jnp.asarray(qs), now_rel,
        )
        if not bool(converged):
            raise ConvergenceError(
                f"sharded reachability did not converge within "
                f"{self.max_iters} iterations"
            )
        return np.asarray(out)[:B, :Q]
