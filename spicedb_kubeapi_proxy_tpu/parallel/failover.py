"""Automatic leader failover for the mirrored (primary/replica) engine.

The multi-host serving story used to die with its leader: ONE TCP-serving
process mirrored writes to followers (`multihost.py`), and PR 3's
catch-up only helped a *follower* rejoin. This module closes the loop —
the RedisGraph/Samyama deployment shape from PAPERS.md, where a
hardware-accelerated graph engine rides a replicated store tier with
real failover — while keeping the paper's proxy semantics: during a
control change the system fails *closed* (503s), never *wrong*.

Three cooperating mechanisms:

- **Fenced terms**: a monotonically increasing integer, persisted per
  data dir (`persistence/manager.py` ``load_term``/``store_term``),
  stamped on every mirror frame, heartbeat, catch-up cut, and follower
  ack. A deposed leader's late output carries an old term and is
  rejected (`multihost.fence_term`, counted by
  ``mirror_frames_rejected_stale_term_total``); a subscriber resuming
  from a deposed term past the promotion baseline gets a forced full
  state transfer (the general form of PR 3's "follower ahead of leader"
  rule) and rebases its local WAL onto the new lineage.
- **Election & promotion** (:class:`FailoverCoordinator`): the leader
  heartbeats over the existing mirror transport; on heartbeat loss each
  follower probes every peer's ``failover_state`` and the best
  reachable candidate promotes — Raft-ordered: highest TERM first (a
  deposed lineage's inflated revision count never outranks the
  canonical lineage), then highest revision, then LOWEST peer id —
  bumps + persists the term, wraps its engine in a sync-replicating
  :class:`~.multihost.MirroredEngine`, and starts answering. Sets of
  3+ additionally require MAJORITY visibility to elect (a minority
  partition keeps electing, fail closed). Losers wait for the winner
  and re-subscribe with catch-up. A returning old leader finds the
  higher term at boot (or on its lease probe), demotes, and converges
  as a follower.
- **Role gating**: a follower's `EngineServer` rejects every op except
  ``failover_state`` with kind ``not_leader`` — clients re-resolve
  (`engine/remote.py` ``FailoverEngine``) instead of reading stale
  state; the proxy's authz middleware turns the same rejection into a
  fail-closed kube 503 + Retry-After.

Durability contract (why "no acked write lost" holds): the leader's
mutations are SYNC-replicated — the client ack waits until every live
follower has applied AND journaled the frame under its own
``--wal-fsync`` policy. With ``always`` on both sides, a SIGKILLed
leader's every acknowledged write is already fsynced on the follower
that promotes. Writes accepted while NO follower is subscribed (the
window after a follower crash) are exactly as durable as the leader's
own WAL — the availability-over-redundancy trade a two-node set makes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..utils.metrics import metrics
from .multihost import (
    LeaderLost,
    MirroredEngine,
    MultiHostError,
    StaleTermError,
    follower_loop,
)

log = logging.getLogger("sdbkp.failover")

ROLE_FOLLOWER = "follower"
ROLE_LEADER = "leader"
ROLE_ELECTING = "electing"
# terminal: the coordinator thread died on an unexpected error — the
# host answers failover_state truthfully (never leads, never follows)
# so peers and orchestrators can see the replica is lost, instead of a
# silently-wedged not_leader-forever process
ROLE_FAILED = "failed"

# engine_role gauge encoding (the ordering is arbitrary — dashboards
# key on the labels, not the sum)
ROLE_GAUGE = {ROLE_FOLLOWER: 0.0, ROLE_LEADER: 1.0, ROLE_ELECTING: 2.0,
              ROLE_FAILED: 3.0}


class FailoverError(MultiHostError):
    pass


def parse_peers(spec: str) -> list[tuple[str, int]]:
    """``host:port,host:port,...`` -> [(host, port)] in PEER-ID ORDER
    (the list index IS the peer id everywhere: tie-breaks, --peer-id,
    failover_state). The ONE owner of the flag format."""
    peers = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]
        if not host or not port.isdigit() or not 0 < int(port) < 65536:
            raise FailoverError(
                f"--peers entry {part!r}: expected host:port")
        peers.append((host, int(port)))
    if not peers:
        raise FailoverError("--peers: at least one host:port required")
    return peers


def choose_candidate(states: dict) -> Optional[int]:
    """Deterministic election over ``peer_id -> {"term", "revision"}``
    candidate states, Raft-ordered: the HIGHEST TERM wins first — a
    deposed lineage's inflated revision count must never beat the
    canonical newer lineage (its extra revisions are exactly the fenced-
    off writes a rebase discards) — then the highest revision within
    that term (most acked history survives), then the LOWEST peer id.
    Every voter computing over the same reachable set picks the same
    winner."""
    best = None
    for pid, st in states.items():
        key = (-int(st.get("term", 0) or 0),
               -int(st.get("revision", 0) or 0), int(pid))
        if best is None or key < best[0]:
            best = (key, int(pid))
    return None if best is None else best[1]


class FailoverCoordinator:
    """Runs ONE engine-host process's role in a replicated set.

    Owns the role state machine (follower -> electing -> leader ->
    deposed -> follower), the persisted term, and the role/term/lag the
    server's ``failover_state`` op and gauges report. The asyncio
    `EngineServer` keeps serving throughout; this object swaps what it
    serves (the bare engine vs a term-stamped MirroredEngine wrapper)
    and gates which ops it answers."""

    def __init__(self, engine, server, peers: list, self_id: int,
                 token: Optional[str] = None,
                 data_dir: Optional[str] = None,
                 heartbeat_interval: float = 2.0,
                 heartbeat_timeout: Optional[float] = None,
                 replication_timeout: float = 10.0,
                 min_sync_replicas: int = 0,
                 client_ssl=None,
                 probe_timeout: float = 2.0,
                 boot_grace: float = 20.0):
        if not 0 <= self_id < len(peers):
            raise FailoverError(
                f"peer id {self_id} out of range for {len(peers)} peers")
        self.engine = engine  # the INNER engine, never the wrapper
        self.server = server
        self.peers = list(peers)
        self.self_id = int(self_id)
        self.token = token
        self.data_dir = data_dir
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = (heartbeat_timeout
                                  or heartbeat_interval * 3 + 1.0)
        self.replication_timeout = replication_timeout
        self.min_sync_replicas = int(min_sync_replicas)
        self.client_ssl = client_ssl
        self.probe_timeout = probe_timeout
        self.boot_grace = boot_grace
        self.role = ROLE_ELECTING
        self.lag = 0
        self.term = 0
        if data_dir:
            from ..persistence.manager import load_term

            self.term = load_term(data_dir)
        self._mirrored: Optional[MirroredEngine] = None
        # set when this node lost an EQUAL-TERM leader conflict (a
        # crashed promotion's persisted term was reused by another
        # peer): its own history under that term is suspect, so every
        # rejoin demands a full state transfer until it next promotes
        self._rejoin_full = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # probe clients: one per OTHER peer, single-attempt, short
        # budgets, breaker that never opens (election must keep asking)
        from ..engine.remote import RemoteEngine
        from ..utils.resilience import CircuitBreaker

        self._probes = {
            pid: RemoteEngine(
                h, p, token=token, ssl_context=client_ssl,
                timeout=probe_timeout, connect_timeout=probe_timeout,
                retries=0,
                breaker=CircuitBreaker(f"peer:{h}:{p}",
                                       failure_threshold=1 << 30))
            for pid, (h, p) in enumerate(self.peers) if pid != self.self_id
        }
        server.failover_status = self.status
        server.mirror_heartbeat = heartbeat_interval
        self._set_role(ROLE_ELECTING)
        metrics.gauge("engine_term").set(self.term)

    # -- observability --------------------------------------------------------

    def status(self) -> dict:
        return {"role": self.role, "term": self.term,
                "revision": self.engine.revision,
                "peer_id": self.self_id, "lag": self.lag}

    def _set_role(self, role: str) -> None:
        if role != self.role:
            log.info("role: %s -> %s (term %d)", self.role, role,
                     self.term)
        self.role = role
        metrics.gauge("engine_role").set(ROLE_GAUGE[role])

    def _adopt_term(self, term: int) -> None:
        term = int(term)
        if term <= self.term:
            return
        self.term = term
        if self.data_dir:
            from ..persistence.manager import store_term

            store_term(self.data_dir, term)
        metrics.gauge("engine_term").set(term)

    def _set_lag(self, lag: int) -> None:
        self.lag = int(lag)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="failover-coordinator")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- probing --------------------------------------------------------------

    def _probe_all(self) -> dict:
        """peer_id -> failover_state for every OTHER reachable peer."""
        states = {}
        for pid, probe in self._probes.items():
            try:
                states[pid] = probe.failover_state()
            except Exception as e:  # noqa: BLE001 - unreachable peer
                log.debug("probe peer %d failed: %s", pid, e)
        return states

    def _leader_among(self, states: dict) -> Optional[int]:
        """The reachable peer claiming leadership with the highest term
        not BELOW ours (an old-term 'leader' is a deposed straggler we
        must not follow)."""
        best = None
        for pid, st in states.items():
            if st.get("role") != ROLE_LEADER:
                continue
            t = int(st.get("term", 0) or 0)
            if t < self.term:
                continue
            if best is None or t > best[1]:
                best = (pid, t)
        return None if best is None else best[0]

    # -- the state machine ----------------------------------------------------

    def run(self) -> None:
        """Blocking role loop (the CLI runs it on a daemon thread next
        to the asyncio server)."""
        try:
            leader_id = self._boot()
            while not self._stop.is_set():
                if leader_id is None:
                    leader_id = self._elect()
                elif leader_id == self.self_id:
                    self._lead()
                    leader_id = None  # deposed (or stopping)
                else:
                    self._follow(leader_id)
                    leader_id = None  # leader lost: elect
        except Exception:
            # terminal and OBSERVABLE: the host keeps answering
            # failover_state with role=failed (peers elect around it,
            # orchestrators see a replica that needs a restart) instead
            # of a dead thread behind a healthy-looking process
            log.exception("failover coordinator died; this replica is "
                          "lost until the process restarts")
            self._set_role(ROLE_FAILED)
            metrics.counter("failover_coordinator_failures_total").inc()
            raise

    def _boot(self) -> Optional[int]:
        """Find the current leader at process start, giving the rest of
        the set ``boot_grace`` to come up: electing from partial
        visibility could crown a candidate with LESS acked history than
        an unreachable-but-booting peer (whose superseded writes a later
        full-state rebase would then discard). An incumbent leader ends
        the wait instantly; so does hearing from EVERY peer — with full
        visibility the revision-ordered election is safe immediately. A
        RESTARTED old leader takes this same path, finds its successor's
        higher term, and demotes instead of split-braining."""
        deadline = time.monotonic() + self.boot_grace
        while not self._stop.is_set():
            states = self._probe_all()
            lid = self._leader_among(states)
            if lid is not None:
                return lid
            if len(states) == len(self._probes):
                return None  # everyone answered, nobody leads: elect
            if time.monotonic() >= deadline:
                log.warning(
                    "boot grace (%.0fs) expired with %d/%d peers "
                    "unreachable; electing from partial visibility",
                    self.boot_grace, len(self._probes) - len(states),
                    len(self._probes))
                return None
            self._stop.wait(min(0.5, self.heartbeat_interval))
        return None

    def _elect(self) -> Optional[int]:
        """One election round: probe, defer to any live leader, else
        promote self iff self is the deterministic winner; otherwise
        wait for the winner to take over."""
        self._set_role(ROLE_ELECTING)
        t0 = time.monotonic()
        while not self._stop.is_set():
            states = self._probe_all()
            lid = self._leader_among(states)
            if lid is not None:
                return lid
            # majority visibility for sets of 3+: a minority partition
            # must keep electing (fail closed) rather than crown a
            # leader the majority side can't see — two live leaders
            # would split the clients by term. A 2-node set has no
            # usable majority once its peer is DEAD (the whole point of
            # failover), so it elects from whatever is visible and
            # leans on --min-sync-replicas/fencing for partition
            # safety (docs/operations.md "Leader failover").
            visible = len(states) + 1
            if len(self.peers) >= 3 and visible <= len(self.peers) // 2:
                log.warning(
                    "election stalled: only %d/%d peers visible (no "
                    "majority); retrying", visible, len(self.peers))
                self._stop.wait(min(0.5, self.heartbeat_interval))
                continue
            candidates = {self.self_id: self.status()}
            for pid, st in states.items():
                if st.get("role") in (ROLE_FOLLOWER, ROLE_ELECTING):
                    candidates[pid] = st
            winner = choose_candidate(candidates)
            if winner == self.self_id:
                self._promote(states)
                metrics.histogram("failover_duration_seconds").observe(
                    time.monotonic() - t0)
                return self.self_id
            # the winner is another peer: give it a beat to promote,
            # then re-probe (it may have died too — the loop converges
            # on whoever remains)
            self._stop.wait(min(0.5, self.heartbeat_interval))
        return None

    def _promote(self, states: dict) -> None:
        """Become leader: bump the term past everything observed,
        persist it FIRST (fencing must survive a crash between promotion
        and the first frame), then serve a sync-replicating mirror."""
        highest = max([self.term] + [int(s.get("term", 0) or 0)
                                     for s in states.values()])
        self._adopt_term(highest + 1)
        self._mirrored = MirroredEngine(
            self.engine, term=self.term, mirror_queries=False,
            sync_replication=True,
            replication_timeout=self.replication_timeout,
            min_sync_replicas=self.min_sync_replicas)
        self.server.engine = self._mirrored
        self.lag = 0
        self._rejoin_full = False  # this node's lineage is canonical now
        self._set_role(ROLE_LEADER)
        metrics.counter("failover_total").inc()
        log.warning("promoted to leader (term %d, revision %d)",
                    self.term, self.engine.revision)

    def _lead(self) -> None:
        """Serve until deposed: a lease-style watch probes peers each
        heartbeat interval; any peer with a HIGHER term means a newer
        lineage exists — stop serving immediately (fail closed), unwrap,
        and rejoin as a follower. Two leaders at the SAME term (a
        crashed promotion persisted a term no peer ever saw, and the
        election reused it) resolve deterministically: the LOWER peer id
        keeps the term and bumps past it so fencing can reject the other
        lineage; the loser demotes with its term-local history marked
        suspect (forced full-state rejoin)."""
        while not self._stop.is_set():
            self._stop.wait(self.heartbeat_interval)
            if self._stop.is_set():
                return
            for pid, st in self._probe_all().items():
                t = int(st.get("term", 0) or 0)
                if t > self.term:
                    log.warning(
                        "deposed: peer %d reports term %d > own %d; "
                        "demoting", pid, t, self.term)
                    self._demote()
                    return
                if st.get("role") == ROLE_LEADER and t == self.term:
                    if self.self_id < pid:
                        log.warning(
                            "equal-term leader conflict with peer %d at "
                            "term %d; keeping leadership and bumping the "
                            "term so fencing can reject its lineage",
                            pid, t)
                        self._adopt_term(self.term + 1)
                        if self._mirrored is not None:
                            self._mirrored.term = self.term
                    else:
                        log.warning(
                            "equal-term leader conflict with peer %d at "
                            "term %d; demoting (lower id wins) with a "
                            "forced full-state rejoin", pid, t)
                        self._rejoin_full = True
                        self._demote()
                        return

    def _demote(self) -> None:
        # role FIRST, engine swap second: the server's in-worker gate
        # re-check reads role then engine, so this order guarantees a
        # request that still sees role=leader also sees the (pinned)
        # mirrored wrapper — never a bare engine on a deposed leader
        self._set_role(ROLE_FOLLOWER)
        self.server.engine = self.engine  # stop serving the wrapper
        if self._mirrored is not None:
            # terminate the deposed wrapper's mirror streams: followers
            # still subscribed would otherwise keep eating its old-term
            # heartbeats (equal terms pass the fence) and never learn a
            # newer lineage exists
            self._mirrored.close_subscribers()
        self._mirrored = None

    def _follow(self, leader_id: int) -> None:
        """Replay the leader's mirror stream until it is lost (-> elect)
        or proves stale (-> elect). Resumes from the local revision with
        our term attached, so a deposed-lineage history triggers the
        leader's forced full-state transfer + local WAL rebase."""
        self._set_role(ROLE_FOLLOWER)
        self.server.engine = self.engine
        host, port = self.peers[leader_id]
        # a node that lost an equal-term conflict cannot trust ANY of
        # its history under that term: from_revision=-1 is below every
        # real revision, so the leader's catch-up decision tree bottoms
        # out in a full state transfer (and the local WAL rebases)
        from_rev = -1 if self._rejoin_full else self.engine.revision
        try:
            follower_loop(
                self.engine, host, port, token=self.token,
                ssl_context=self.client_ssl,
                from_revision=from_rev,
                current_term=self.term,
                heartbeat_timeout=self.heartbeat_timeout,
                ack=True, fail_on_loss=True,
                on_term=self._adopt_term,
                on_progress=self._set_lag,
                connect_deadline=self.heartbeat_timeout)
        except StaleTermError as e:
            log.warning("leader %d is stale: %s", leader_id, e)
        except (LeaderLost, MultiHostError, OSError) as e:
            metrics.counter("mirror_leader_losses_total").inc()
            log.warning("lost leader %d (%s: %s)", leader_id,
                        type(e).__name__, e)
        except Exception:  # noqa: BLE001 - replay/rebase faults
            # a store/persistence error mid-replay (disk full during a
            # rebase, a corrupt frame) must not kill the coordinator
            # thread: log it loudly and fall back to election — the
            # retry either heals (transient) or keeps the failure
            # visible in the logs (persistent), instead of wedging the
            # process as a silent not_leader-forever replica
            metrics.counter("mirror_follow_errors_total").inc()
            log.exception("follower replay failed against leader %d; "
                          "re-electing", leader_id)
