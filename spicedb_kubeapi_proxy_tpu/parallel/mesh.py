"""Mesh construction for the sharded engine.

One 2-D mesh, axes ``("data", "graph")``:

- ``data``  — data parallelism over concurrent queries (requests).
- ``graph`` — edge-tensor parallelism within one query (the model/tensor
  axis of this workload: the graph, not weights, is the big operand).

Axis sizes must multiply to the device count. By default the graph axis
takes as many devices as possible while keeping the data axis at least 2
when there are at least 4 devices — list-filter latency (BASELINE.md
target) is bounded by per-query propagation, which only the graph axis
accelerates, while throughput under concurrency (BASELINE config 5) comes
from the data axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


class MeshSpecError(ValueError):
    pass


def mesh_topology(mesh: "Mesh") -> dict:
    """One-line description of a mesh's device topology — the label
    benches and logs attach to mesh-path measurements so a number is
    never read without its (device count, axis split, platform)
    provenance: ``{"devices": N, "data": D, "graph": G,
    "platform": "cpu"|"tpu"|...}``."""
    devs = mesh.devices.reshape(-1)
    return {
        "devices": int(devs.size),
        "data": int(mesh.shape["data"]),
        "graph": int(mesh.shape["graph"]),
        "platform": str(devs[0].platform) if devs.size else "none",
    }


def parse_mesh_spec(spec: str) -> dict:
    """"auto" -> {} (all devices, derived axes); "data=D,graph=G" ->
    explicit axis sizes (either may be omitted). Raises MeshSpecError."""
    if spec == "auto":
        return {}
    out: dict = {}
    for part in spec.split(","):
        k, sep, v = part.partition("=")
        if not sep or k.strip() not in ("data", "graph") \
                or not v.strip().isdigit() or int(v) < 1:
            raise MeshSpecError(
                f"invalid engine mesh {spec!r} "
                "(expected 'auto' or 'data=D,graph=G')")
        out[k.strip()] = int(v)
    return out


def make_mesh(
    n_devices: Optional[int] = None,
    data: Optional[int] = None,
    graph: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build the ``("data", "graph")`` mesh over ``n_devices`` devices.

    Any of ``data`` / ``graph`` may be given; missing sizes are derived.
    ``devices`` overrides the device list (defaults to ``jax.devices()``).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if len(devices) < n_devices:
        raise ValueError(
            f"requested {n_devices} devices, only {len(devices)} available"
        )
    if data is None and graph is None:
        data = 2 if n_devices >= 4 and n_devices % 2 == 0 else 1
        graph = n_devices // data
    elif data is None:
        if n_devices % graph:
            raise ValueError(f"graph={graph} does not divide {n_devices}")
        data = n_devices // graph
    elif graph is None:
        if n_devices % data:
            raise ValueError(f"data={data} does not divide {n_devices}")
        graph = n_devices // data
    if data * graph != n_devices:
        raise ValueError(
            f"data*graph = {data}*{graph} != n_devices = {n_devices}"
        )
    import numpy as np

    arr = np.asarray(devices).reshape(data, graph)
    return Mesh(arr, axis_names=("data", "graph"))
