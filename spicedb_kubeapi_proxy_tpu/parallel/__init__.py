"""Device-mesh sharding of the reachability engine.

The reference scales by delegating graph traversal to SpiceDB and fanning
requests out over goroutines (SURVEY.md §2.5); here the same two scale
dimensions map onto a 2-D ``jax.sharding.Mesh``:

- ``graph`` axis — the edge tensor is sharded across chips (the reference's
  "bigger graph than one machine" dimension; SpiceDB horizontal dispatch).
  Each chip propagates over its edge shard and the shards are joined with a
  collective max over ICI each fixpoint step.
- ``data`` axis — the query batch (concurrent requests: bulk checks, list
  prefilters) is sharded across chips, the analog of the reference's
  per-request goroutine fan-out (pkg/authz/check.go:77-93).
"""

from .mesh import make_mesh
from .sharded import ShardedGraph

__all__ = ["make_mesh", "ShardedGraph"]
