"""Rules engine: ProxyRule config, template/expression compiler, matcher.

Mirrors the reference's pkg/config/proxyrule (YAML rule schema) and
pkg/rules (compilation of templates/tupleSets/conditions into runnable
rules keyed by (verb, group, version, resource)). The reference embeds two
third-party expression runtimes — Bloblang for templates/tupleSets and CEL
for `if` conditions; here a single host expression language (expr.py)
covers both surfaces.
"""

from .expr import ExprError, compile_expr, compile_template  # noqa: F401
from .input import RequestInfo, ResolveInput, UserInfo  # noqa: F401
from .proxyrule import (  # noqa: F401
    Match,
    PreFilterSpec,
    PostFilterSpec,
    RuleConfig,
    RuleSpec,
    RuleValidationError,
    StringOrTemplate,
    UpdateSpec,
    parse_rule_configs,
)
from .compile import (  # noqa: F401
    CompileError,
    PostFilter,
    PreFilter,
    RelExpr,
    ResolvedRel,
    RunnableRule,
    TupleSetExpr,
    UpdateSet,
    compile_rule,
)
from .matcher import MapMatcher, RequestMeta  # noqa: F401
