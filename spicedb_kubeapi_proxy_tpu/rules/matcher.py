"""Request matcher: (verb, group, version, resource) -> runnable rules.

Mirrors the reference's Matcher/MapMatcher (rules.go:55-117): a hash map
from normalized request meta to the precompiled rules that apply. The
Matcher interface point (a `matcher` attribute the server can swap at
runtime, reference server.go:139-140) is preserved by keeping this a small
class with a `match` method.
"""

from __future__ import annotations

from dataclasses import dataclass

from .compile import RunnableRule, compile_rule
from .input import RequestInfo
from .proxyrule import RuleConfig, parse_rule_configs


@dataclass(frozen=True)
class RequestMeta:
    verb: str
    api_group: str
    api_version: str
    resource: str

    @staticmethod
    def from_request(r: RequestInfo) -> "RequestMeta":
        return RequestMeta(r.verb, r.api_group, r.api_version, r.resource)


def split_group_version(group_version: str) -> tuple[str, str]:
    """'v1' -> ('', 'v1'); 'apps/v1' -> ('apps', 'v1')."""
    if "/" in group_version:
        g, v = group_version.split("/", 1)
        return g, v
    return "", group_version


class MapMatcher:
    def __init__(self, configs: list[RuleConfig]):
        self._rules: dict[RequestMeta, list[RunnableRule]] = {}
        for cfg in configs:
            compiled = compile_rule(cfg)
            for m in cfg.spec.matches:
                group, version = split_group_version(m.group_version)
                for verb in m.verbs:
                    key = RequestMeta(verb, group, version, m.resource)
                    self._rules.setdefault(key, []).append(compiled)

    @staticmethod
    def from_yaml(text: str) -> "MapMatcher":
        return MapMatcher(parse_rule_configs(text))

    def match(self, meta: RequestMeta) -> list[RunnableRule]:
        return self._rules.get(meta, [])
