"""The host expression language for rule templates, tupleSets and conditions.

The reference embeds two expression runtimes: Bloblang for relationship
templates / tupleSets (with custom ``split_name`` / ``split_namespace``
functions, /root/reference/pkg/rules/env.go:13-58) and CEL for ``if``
conditions (rules.go:45-51,417-464). SURVEY.md §7 calls for ONE host
language keeping the ``{{ }}``/literal duality (rules.go:1005-1026); this
module implements it: a small expression language whose surface covers both
uses —

- field access & indexing:      ``user.name``, ``object.metadata.labels["x"]``
- root reference:               ``this`` (the whole input document)
- lambdas / iteration:          ``items.map_each(this.name)``, ``.filter(...)``
- context capture:              ``expr.(nsName -> body)``
- let bindings (multi-line):    ``let ns = this.namespace`` then ``$ns``/``ns``
- fallback on error/null:       ``expr | default``
- conditionals:                 ``if c { a } else { b }`` and CEL ``c ? a : b``
- operators:  ``== != < <= > >= && || ! in + - * / %``
- methods: ``string() number() length() split(s) join(s) trim() uppercase()
  lowercase() contains(x) startsWith(x) endsWith(x) matches(re) or(d)
  keys() values() exists(k)``
- functions: ``split_name(s)``, ``split_namespace(s)`` (the custom Bloblang
  env), ``has(x)``, ``size(x)``, ``string(x)``, ``int(x)``

Compilation happens once at rule-load (boot), evaluation per request.
"""

from __future__ import annotations

import json
import re as _re
from dataclasses import dataclass
from typing import Any, Callable, Optional


class ExprError(ValueError):
    pass


class _Missing:
    """Null-ish result of accessing an absent field; attribute access chains
    silently, most other uses raise (recoverable via the `|` operator)."""

    _instance: "_Missing" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<missing>"


MISSING = _Missing()


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = _re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+(?:\.\d+)?)
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<dollar>\$[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>->|\|\||&&|[=!<>]=|[.()\[\]{},:?|<>!+*/%$=-])
    """,
    _re.VERBOSE,
)

@dataclass
class _Tok:
    kind: str
    value: str


def _tokenize(text: str) -> list[_Tok]:
    out: list[_Tok] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise ExprError(f"unexpected character {text[pos]!r} in expression")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append(_Tok(kind, m.group()))
    out.append(_Tok("eof", ""))
    return out


# ---------------------------------------------------------------------------
# AST (closures — each node compiles to a Python callable of (env))
# ---------------------------------------------------------------------------


class _Env:
    __slots__ = ("data", "vars", "this")

    def __init__(self, data, vars_=None, this=None):
        self.data = data
        self.vars = vars_ or {}
        self.this = data if this is None else this


_Node = Callable[[_Env], Any]


def _truthy(v) -> bool:
    if v is MISSING or v is None:
        return False
    if isinstance(v, bool):
        return v
    raise ExprError(f"expected boolean, got {type(v).__name__}: {v!r}")


def _tostr(v) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        if isinstance(v, float) and v.is_integer():
            return str(int(v))
        return str(v)
    if v is None or v is MISSING:
        raise ExprError("cannot convert null to string")
    return json.dumps(v)


class _Parser:
    def __init__(self, text: str):
        self.toks = _tokenize(text)
        self.i = 0
        # root identifiers the compiled program may read from the DATA map
        # (over-collection is fine — let-bound names land here too; callers
        # use this to prove an expr depends on nothing but, say,
        # resourceId, so extra names only disable an optimization)
        self.refs: set = set()

    @property
    def cur(self) -> _Tok:
        return self.toks[self.i]

    def advance(self) -> _Tok:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def accept(self, value: str) -> bool:
        if self.cur.value == value and self.cur.kind in ("op", "ident"):
            self.advance()
            return True
        return False

    def expect(self, value: str):
        if not self.accept(value):
            raise ExprError(f"expected {value!r}, got {self.cur.value!r}")

    # program := (let IDENT = expr)* expr
    def parse_program(self) -> _Node:
        lets: list[tuple[str, _Node]] = []
        while self.cur.kind == "ident" and self.cur.value == "let":
            self.advance()
            if self.cur.kind != "ident":
                raise ExprError("expected identifier after let")
            name = self.advance().value
            self.expect("=")
            lets.append((name, self.parse_expr()))
        body = self.parse_expr()
        if self.cur.kind != "eof":
            raise ExprError(f"unexpected trailing input: {self.cur.value!r}")
        if not lets:
            return body

        def run(env: _Env):
            env2 = _Env(env.data, dict(env.vars), env.this)
            for name, node in lets:
                env2.vars[name] = node(env2)
            return body(env2)

        return run

    def parse_expr(self) -> _Node:
        return self.parse_ternary()

    def parse_ternary(self) -> _Node:
        cond = self.parse_or()
        if self.accept("?"):
            a = self.parse_expr()
            self.expect(":")
            b = self.parse_expr()
            return lambda env: a(env) if _truthy(cond(env)) else b(env)
        return cond

    def parse_or(self) -> _Node:
        left = self.parse_and()
        while self.accept("||"):
            right = self.parse_and()
            left = (lambda l, r: lambda env: _truthy(l(env)) or _truthy(r(env)))(
                left, right)
        return left

    def parse_and(self) -> _Node:
        left = self.parse_not()
        while self.accept("&&"):
            right = self.parse_not()
            left = (lambda l, r: lambda env: _truthy(l(env)) and _truthy(r(env)))(
                left, right)
        return left

    def parse_not(self) -> _Node:
        if self.accept("!"):
            inner = self.parse_not()
            return lambda env: not _truthy(inner(env))
        return self.parse_cmp()

    def parse_cmp(self) -> _Node:
        left = self.parse_add()
        op = self.cur.value
        if self.cur.kind == "op" and op in ("==", "!=", "<", "<=", ">", ">="):
            self.advance()
            right = self.parse_add()

            def cmp(env, l=left, r=right, op=op):
                a, b = l(env), r(env)
                if a is MISSING:
                    a = None
                if b is MISSING:
                    b = None
                if op == "==":
                    return a == b
                if op == "!=":
                    return a != b
                if a is None or b is None:
                    raise ExprError(f"cannot order null ({op})")
                try:
                    if op == "<":
                        return a < b
                    if op == "<=":
                        return a <= b
                    if op == ">":
                        return a > b
                    return a >= b
                except TypeError:
                    raise ExprError(
                        f"cannot compare {type(a).__name__} {op} {type(b).__name__}"
                    ) from None

            return cmp
        if self.cur.kind == "ident" and op == "in":
            self.advance()
            right = self.parse_add()

            def contains(env, l=left, r=right):
                a, b = l(env), r(env)
                if isinstance(b, dict):
                    return a in b
                if isinstance(b, (list, tuple, str)):
                    return a in b
                raise ExprError(f"'in' needs list/map/string, got {type(b).__name__}")

            return contains
        return left

    def parse_add(self) -> _Node:
        left = self.parse_mul()
        while self.cur.kind == "op" and self.cur.value in ("+", "-"):
            op = self.advance().value
            right = self.parse_mul()

            def arith(env, l=left, r=right, op=op):
                a, b = l(env), r(env)
                if op == "+":
                    if isinstance(a, str) and isinstance(b, str):
                        return a + b
                    if isinstance(a, list) and isinstance(b, list):
                        return a + b
                    if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                       and not isinstance(a, bool) and not isinstance(b, bool):
                        return a + b
                    raise ExprError(
                        f"cannot add {type(a).__name__} + {type(b).__name__} "
                        "(use .string() to concatenate)"
                    )
                if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                    return a - b
                raise ExprError(f"cannot subtract {type(a).__name__}")

            left = arith
        return left

    def parse_mul(self) -> _Node:
        left = self.parse_unary()
        while self.cur.kind == "op" and self.cur.value in ("*", "/", "%"):
            op = self.advance().value
            right = self.parse_unary()

            def arith(env, l=left, r=right, op=op):
                a, b = l(env), r(env)
                if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
                    raise ExprError(f"arithmetic on {type(a).__name__}")
                if op == "*":
                    return a * b
                if op == "/":
                    if b == 0:
                        raise ExprError("division by zero")
                    return a / b
                if b == 0:
                    raise ExprError("modulo by zero")
                return a % b

            left = arith
        return left

    def parse_unary(self) -> _Node:
        if self.cur.kind == "op" and self.cur.value == "-":
            self.advance()
            inner = self.parse_unary()

            def neg(env):
                v = inner(env)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    raise ExprError("cannot negate non-number")
                return -v

            return neg
        return self.parse_pipe()

    def parse_pipe(self) -> _Node:
        left = self.parse_postfix()
        while self.cur.kind == "op" and self.cur.value == "|":
            self.advance()
            right = self.parse_postfix()

            def fallback(env, l=left, r=right):
                try:
                    v = l(env)
                except ExprError:
                    return r(env)
                if v is MISSING or v is None:
                    return r(env)
                return v

            left = fallback
        return left

    def parse_postfix(self) -> _Node:
        node = self.parse_primary()
        while True:
            if self.cur.kind == "op" and self.cur.value == ".":
                self.advance()
                # context capture: .(name -> body)
                if self.cur.kind == "op" and self.cur.value == "(":
                    self.advance()
                    if self.cur.kind != "ident":
                        raise ExprError("expected identifier in capture")
                    name = self.advance().value
                    self.expect("->")
                    body = self.parse_expr()
                    self.expect(")")

                    def capture(env, recv=node, name=name, body=body):
                        v = recv(env)
                        env2 = _Env(env.data, dict(env.vars), env.this)
                        env2.vars[name] = v
                        return body(env2)

                    node = capture
                    continue
                if self.cur.kind != "ident":
                    raise ExprError(f"expected field name after '.', got "
                                    f"{self.cur.value!r}")
                name = self.advance().value
                if self.cur.kind == "op" and self.cur.value == "(":
                    node = self.parse_method(node, name)
                else:
                    node = (lambda recv, name: lambda env: _get_field(
                        recv(env), name))(node, name)
                continue
            if self.cur.kind == "op" and self.cur.value == "[":
                self.advance()
                key = self.parse_expr()
                self.expect("]")

                def index(env, recv=node, key=key):
                    v, k = recv(env), key(env)
                    if isinstance(v, dict):
                        return v.get(k, MISSING)
                    if isinstance(v, (list, tuple, str)):
                        if not isinstance(k, int) or isinstance(k, bool):
                            raise ExprError("list index must be an integer")
                        if -len(v) <= k < len(v):
                            return v[k]
                        return MISSING
                    if v is MISSING or v is None:
                        return MISSING
                    raise ExprError(f"cannot index {type(v).__name__}")

                node = index
                continue
            return node

    def parse_method(self, recv: _Node, name: str) -> _Node:
        """Method call — lambda-taking methods get `this` rebound."""
        self.expect("(")
        if name in ("map_each", "filter"):
            body = self.parse_expr()
            self.expect(")")

            def run(env, recv=recv, name=name, body=body):
                v = recv(env)
                if v is MISSING or v is None:
                    raise ExprError(f".{name}() on null")
                if not isinstance(v, (list, tuple)):
                    raise ExprError(f".{name}() needs a list, got {type(v).__name__}")
                out = []
                for item in v:
                    env2 = _Env(env.data, env.vars, item)
                    if name == "map_each":
                        out.append(body(env2))
                    elif _truthy(body(env2)):
                        out.append(item)
                return out

            return run
        args: list[_Node] = []
        if not (self.cur.kind == "op" and self.cur.value == ")"):
            args.append(self.parse_expr())
            while self.accept(","):
                args.append(self.parse_expr())
        self.expect(")")

        def run(env, recv=recv, name=name, args=args):
            return _call_method(recv(env), name, [a(env) for a in args])

        return run

    def parse_primary(self) -> _Node:
        t = self.cur
        if t.kind == "num":
            self.advance()
            v = float(t.value) if "." in t.value else int(t.value)
            return lambda env: v
        if t.kind == "str":
            self.advance()
            raw = t.value[1:-1]
            s = _unescape(raw)
            return lambda env: s
        if t.kind == "dollar":
            self.advance()
            name = t.value[1:]

            def var(env):
                if name not in env.vars:
                    raise ExprError(f"unknown variable ${name}")
                return env.vars[name]

            return var
        if t.kind == "op" and t.value == "(":
            self.advance()
            inner = self.parse_expr()
            self.expect(")")
            return inner
        if t.kind == "op" and t.value == "[":
            self.advance()
            items: list[_Node] = []
            if not (self.cur.kind == "op" and self.cur.value == "]"):
                items.append(self.parse_expr())
                while self.accept(","):
                    items.append(self.parse_expr())
            self.expect("]")
            return lambda env: [i(env) for i in items]
        if t.kind == "ident":
            if t.value == "true":
                self.advance()
                return lambda env: True
            if t.value == "false":
                self.advance()
                return lambda env: False
            if t.value == "null":
                self.advance()
                return lambda env: None
            if t.value == "if":
                return self.parse_if()
            if t.value == "this":
                self.advance()
                return lambda env: env.this
            name = self.advance().value
            if self.cur.kind == "op" and self.cur.value == "(":
                return self.parse_function(name)
            self.refs.add(name)

            def ident(env):
                if name in env.vars:
                    return env.vars[name]
                if isinstance(env.data, dict) and name in env.data:
                    return env.data[name]
                return MISSING

            return ident
        raise ExprError(f"unexpected token {t.value!r}")

    def parse_if(self) -> _Node:
        self.expect("if")
        cond = self.parse_expr()
        self.expect("{")
        a = self.parse_expr()
        self.expect("}")
        b: _Node = lambda env: None
        if self.accept("else"):
            self.expect("{")
            b = self.parse_expr()
            self.expect("}")
        return lambda env: a(env) if _truthy(cond(env)) else b(env)

    def parse_function(self, name: str) -> _Node:
        self.expect("(")
        args: list[_Node] = []
        if not (self.cur.kind == "op" and self.cur.value == ")"):
            args.append(self.parse_expr())
            while self.accept(","):
                args.append(self.parse_expr())
        self.expect(")")
        raw_fn = _FUNCTIONS.get(name)
        if raw_fn is None:
            raise ExprError(f"unknown function {name!r}")

        def fn(vals, _raw=raw_fn, _name=name):
            try:
                return _raw(vals)
            except ExprError:
                raise
            except (TypeError, ValueError, AttributeError, KeyError,
                    IndexError) as e:
                raise ExprError(f"{_name}(): {e}") from None

        if name == "has":
            # CEL has(): never throws on missing paths
            arg = args[0]

            def has(env):
                try:
                    v = arg(env)
                except ExprError:
                    return False
                return v is not MISSING and v is not None

            return has
        return lambda env: fn([a(env) for a in args])


def _unescape(raw: str) -> str:
    return (
        raw.replace("\\\\", "\x00")
        .replace('\\"', '"')
        .replace("\\'", "'")
        .replace("\\n", "\n")
        .replace("\\t", "\t")
        .replace("\x00", "\\")
    )


def _get_field(v, name: str):
    if isinstance(v, dict):
        return v.get(name, MISSING)
    if v is MISSING or v is None:
        return MISSING  # silent chaining; pipe/has recover
    raise ExprError(f"cannot access field {name!r} on {type(v).__name__}")


def _call_method(v, name: str, args: list):
    if name == "or":
        # .or(default) exists precisely to absorb missing/null receivers
        if v is MISSING or v is None:
            return args[0]
        return v
    if v is MISSING or v is None:
        raise ExprError(f".{name}() on null")
    try:
        m = _METHODS[name]
    except KeyError:
        raise ExprError(f"unknown method .{name}()") from None
    try:
        return m(v, args)
    except ExprError:
        raise
    except (TypeError, ValueError, AttributeError, KeyError, IndexError) as e:
        # runtime type mismatches surface as recoverable expression errors
        # (so the `|` fallback and rule-level handlers catch them)
        raise ExprError(f".{name}(): {e}") from None


def _m_split(v, args):
    if not isinstance(v, str):
        raise ExprError(".split() on non-string")
    return v.split(args[0])


_METHODS: dict[str, Callable] = {
    "string": lambda v, a: _tostr(v),
    "number": lambda v, a: float(v) if isinstance(v, str) else v + 0,
    "length": lambda v, a: len(v),
    "size": lambda v, a: len(v),
    "split": _m_split,
    "join": lambda v, a: a[0].join(_tostr(x) for x in v),
    "trim": lambda v, a: v.strip(),
    "uppercase": lambda v, a: v.upper(),
    "lowercase": lambda v, a: v.lower(),
    "contains": lambda v, a: a[0] in v,
    "startsWith": lambda v, a: v.startswith(a[0]),
    "starts_with": lambda v, a: v.startswith(a[0]),
    "endsWith": lambda v, a: v.endswith(a[0]),
    "ends_with": lambda v, a: v.endswith(a[0]),
    "matches": lambda v, a: bool(_re.search(a[0], v)),
    "keys": lambda v, a: sorted(v.keys()),
    "values": lambda v, a: [v[k] for k in sorted(v.keys())],
    "exists": lambda v, a: a[0] in v,
}


def _split_name(args):
    (s,) = args
    if not isinstance(s, str):
        raise ExprError("split_name() needs a string")
    return s.split("/", 1)[1] if "/" in s else s


def _split_namespace(args):
    (s,) = args
    if not isinstance(s, str):
        raise ExprError("split_namespace() needs a string")
    return s.split("/", 1)[0] if "/" in s else ""


_FUNCTIONS: dict[str, Callable] = {
    # the custom Bloblang env functions (reference pkg/rules/env.go:13-58):
    # ids shaped `namespace/name` split into parts; no '/' => cluster-scoped
    "split_name": _split_name,
    "split_namespace": _split_namespace,
    "has": lambda args: args[0] is not MISSING and args[0] is not None,
    "size": lambda args: len(args[0]),
    "string": lambda args: _tostr(args[0]),
    "int": lambda args: int(args[0]),
}


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@dataclass
class CompiledExpr:
    source: str
    _node: _Node
    # root data-map identifiers the program may read (conservative
    # over-approximation; literals have none). The watch hub uses this to
    # share allowed-set recomputes across watchers when the id-mapping
    # exprs provably depend only on resourceId.
    refs: frozenset = frozenset()

    def evaluate(self, data: dict, this=None) -> Any:
        v = self._node(_Env(data, this=this))
        return None if v is MISSING else v

    def evaluate_str(self, data: dict) -> str:
        v = self.evaluate(data)
        if v is None:
            raise ExprError(f"expression {self.source!r} evaluated to null")
        return _tostr(v)

    def evaluate_bool(self, data: dict) -> bool:
        v = self.evaluate(data)
        if not isinstance(v, bool):
            raise ExprError(
                f"condition {self.source!r} must evaluate to a boolean, "
                f"got {type(v).__name__}"
            )
        return v


def compile_expr(text: str) -> CompiledExpr:
    """Compile a bare expression (tupleSets, `if` conditions)."""
    p = _Parser(text)
    try:
        node = p.parse_program()
    except ExprError as e:
        raise ExprError(f"in expression {text!r}: {e}") from None
    return CompiledExpr(text, node, frozenset(p.refs))


def compile_template(text: str) -> CompiledExpr:
    """Compile a template field with the reference's ``{{ }}``/literal
    duality (rules.go:1005-1026): a field that starts with ``{{`` and ends
    with ``}}`` is an expression; anything else is a literal string."""
    t = text.strip()
    if t.startswith("{{") and t.endswith("}}"):
        inner = t[2:-2].strip()
        if not inner:
            return CompiledExpr(text, lambda env: "")
        return compile_expr(inner)
    return CompiledExpr(text, lambda env, v=text: v)
