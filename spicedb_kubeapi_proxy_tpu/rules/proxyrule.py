"""ProxyRule config schema: YAML/JSON multi-doc parsing + validation.

Mirrors /root/reference/pkg/config/proxyrule/rule.go: ``authzed.com/v1alpha1
ProxyRule`` documents with match (GVR + verbs), optional CEL-style ``if``
conditions, check/postcheck templates, prefilter (LookupResources mapping),
postfilter (per-object check), and update (creates/touches/deletes/
deleteByFilter + preconditions) with Optimistic/Pessimistic lock modes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import yaml

API_VERSION = "authzed.com/v1alpha1"
KIND = "ProxyRule"

# LookupResources requests use `$` as the resource ID to signal "match the
# object being processed" (reference rule.go:22-24)
MATCHING_ID_FIELD_VALUE = "$"

LOCK_PESSIMISTIC = "Pessimistic"
LOCK_OPTIMISTIC = "Optimistic"

VALID_VERBS = ("get", "list", "watch", "create", "update", "patch", "delete")
WRITE_VERBS = ("create", "update", "patch", "delete")


class RuleValidationError(ValueError):
    pass


@dataclass
class Match:
    group_version: str  # apiVersion, e.g. "v1" or "apps/v1"
    resource: str
    verbs: list[str]


@dataclass
class StringOrTemplate:
    """Exactly one of: template string, tupleSet expression, or structured
    relationship template (reference rule.go:167-172,242-272)."""

    template: str = ""
    tuple_set: str = ""
    rel_template: Optional[dict] = None  # {resource:{type,id,relation}, subject:{...}}


@dataclass
class PreFilterSpec:
    from_object_id_name_expr: str = ""
    from_object_id_namespace_expr: str = ""
    lookup_matching_resources: Optional[StringOrTemplate] = None


@dataclass
class PostFilterSpec:
    check_permission_template: Optional[StringOrTemplate] = None


@dataclass
class UpdateSpec:
    precondition_exists: list[StringOrTemplate] = field(default_factory=list)
    precondition_does_not_exist: list[StringOrTemplate] = field(default_factory=list)
    creates: list[StringOrTemplate] = field(default_factory=list)
    touches: list[StringOrTemplate] = field(default_factory=list)
    deletes: list[StringOrTemplate] = field(default_factory=list)
    delete_by_filter: list[StringOrTemplate] = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.creates or self.touches or self.deletes
                    or self.delete_by_filter)


@dataclass
class RuleSpec:
    locking: str = ""  # "", Optimistic, Pessimistic
    matches: list[Match] = field(default_factory=list)
    ifs: list[str] = field(default_factory=list)
    checks: list[StringOrTemplate] = field(default_factory=list)
    post_checks: list[StringOrTemplate] = field(default_factory=list)
    pre_filters: list[PreFilterSpec] = field(default_factory=list)
    post_filters: list[PostFilterSpec] = field(default_factory=list)
    update: UpdateSpec = field(default_factory=UpdateSpec)


@dataclass
class RuleConfig:
    name: str
    spec: RuleSpec


def _as_string_or_template(v, where: str) -> StringOrTemplate:
    if not isinstance(v, dict):
        raise RuleValidationError(f"{where}: expected a mapping, got {type(v).__name__}")
    tpl = v.get("tpl", "") or ""
    ts = v.get("tupleSet", "") or ""
    has_rel = "resource" in v or "subject" in v
    count = sum([bool(tpl), bool(ts), has_rel])
    if count == 0:
        raise RuleValidationError(
            f"{where}: one of tpl, tupleSet, or resource/subject is required")
    if count > 1:
        raise RuleValidationError(
            f"{where}: tpl, tupleSet, and resource/subject are mutually exclusive")
    rel = None
    if has_rel:
        for part in ("resource", "subject"):
            if not isinstance(v.get(part), dict):
                raise RuleValidationError(f"{where}: {part} must be a mapping")
        rel = {"resource": v["resource"], "subject": v["subject"]}
    return StringOrTemplate(template=str(tpl), tuple_set=str(ts), rel_template=rel)


def _as_sot_list(v, where: str) -> list[StringOrTemplate]:
    if v is None:
        return []
    if not isinstance(v, list):
        raise RuleValidationError(f"{where}: expected a list")
    return [_as_string_or_template(x, f"{where}[{i}]") for i, x in enumerate(v)]


def parse_rule_configs(text: str) -> list[RuleConfig]:
    """Parse multi-document YAML/JSON rule config (reference Parse,
    rule.go:215-239)."""
    rules: list[RuleConfig] = []
    for di, doc in enumerate(yaml.safe_load_all(text)):
        if doc is None:
            continue
        if not isinstance(doc, dict):
            raise RuleValidationError(f"document {di}: expected a mapping")
        where = f"rule {di}"
        api_version = doc.get("apiVersion", "")
        kind = doc.get("kind", "")
        if api_version and api_version != API_VERSION:
            raise RuleValidationError(
                f"{where}: unsupported apiVersion {api_version!r}")
        if kind and kind != KIND:
            raise RuleValidationError(f"{where}: unsupported kind {kind!r}")
        meta = doc.get("metadata") or {}
        name = str(meta.get("name", f"rule-{di}"))
        where = f"rule {name!r}"

        lock = doc.get("lock", "") or ""
        if lock not in ("", LOCK_OPTIMISTIC, LOCK_PESSIMISTIC):
            raise RuleValidationError(f"{where}: invalid lock mode {lock!r}")

        raw_matches = doc.get("match")
        if not isinstance(raw_matches, list) or not raw_matches:
            raise RuleValidationError(f"{where}: match is required and non-empty")
        matches = []
        for mi, m in enumerate(raw_matches):
            if not isinstance(m, dict):
                raise RuleValidationError(f"{where}: match[{mi}] must be a mapping")
            gv = m.get("apiVersion")
            res = m.get("resource")
            verbs = m.get("verbs")
            if not gv or not res:
                raise RuleValidationError(
                    f"{where}: match[{mi}] needs apiVersion and resource")
            if not isinstance(verbs, list) or not verbs:
                raise RuleValidationError(f"{where}: match[{mi}] needs verbs")
            for v in verbs:
                if v not in VALID_VERBS:
                    raise RuleValidationError(
                        f"{where}: match[{mi}] invalid verb {v!r}")
            matches.append(Match(str(gv), str(res), [str(v) for v in verbs]))

        ifs = doc.get("if") or []
        if not isinstance(ifs, list):
            raise RuleValidationError(f"{where}: if must be a list of expressions")

        pre_filters = []
        for pi, p in enumerate(doc.get("prefilter") or []):
            if not isinstance(p, dict):
                raise RuleValidationError(f"{where}: prefilter[{pi}] must be a mapping")
            lmr = p.get("lookupMatchingResources")
            pf = PreFilterSpec(
                from_object_id_name_expr=str(p.get("fromObjectIDNameExpr", "") or ""),
                from_object_id_namespace_expr=str(
                    p.get("fromObjectIDNamespaceExpr", "") or ""),
                lookup_matching_resources=(
                    _as_string_or_template(
                        lmr, f"{where}: prefilter[{pi}].lookupMatchingResources")
                    if lmr is not None else None
                ),
            )
            if pf.lookup_matching_resources is None:
                raise RuleValidationError(
                    f"{where}: prefilter[{pi}] needs lookupMatchingResources")
            if not pf.from_object_id_name_expr:
                raise RuleValidationError(
                    f"{where}: prefilter[{pi}] needs fromObjectIDNameExpr")
            pre_filters.append(pf)

        post_filters = []
        for pi, p in enumerate(doc.get("postfilter") or []):
            if not isinstance(p, dict) or "checkPermissionTemplate" not in p:
                raise RuleValidationError(
                    f"{where}: postfilter[{pi}] needs checkPermissionTemplate")
            post_filters.append(PostFilterSpec(_as_string_or_template(
                p["checkPermissionTemplate"],
                f"{where}: postfilter[{pi}].checkPermissionTemplate")))

        upd = doc.get("update") or {}
        if not isinstance(upd, dict):
            raise RuleValidationError(f"{where}: update must be a mapping")
        update = UpdateSpec(
            precondition_exists=_as_sot_list(
                upd.get("preconditionExists"), f"{where}: preconditionExists"),
            precondition_does_not_exist=_as_sot_list(
                upd.get("preconditionDoesNotExist"),
                f"{where}: preconditionDoesNotExist"),
            creates=_as_sot_list(upd.get("creates"), f"{where}: creates"),
            touches=_as_sot_list(upd.get("touches"), f"{where}: touches"),
            deletes=_as_sot_list(upd.get("deletes"), f"{where}: deletes"),
            delete_by_filter=_as_sot_list(
                upd.get("deleteByFilter"), f"{where}: deleteByFilter"),
        )

        post_checks = _as_sot_list(doc.get("postcheck"), f"{where}: postcheck")
        if post_checks:
            # PostChecks only apply to read single-object operations
            # (reference validatePostCheckVerbs, rules.go:1076-1093)
            for m in matches:
                bad = [v for v in m.verbs
                       if v in WRITE_VERBS or v in ("list", "watch")]
                if bad:
                    raise RuleValidationError(
                        f"{where}: postcheck is incompatible with verbs {bad}")

        spec = RuleSpec(
            locking=lock,
            matches=matches,
            ifs=[str(x) for x in ifs],
            checks=_as_sot_list(doc.get("check"), f"{where}: check"),
            post_checks=post_checks,
            pre_filters=pre_filters,
            post_filters=post_filters,
            update=update,
        )
        rules.append(RuleConfig(name=name, spec=spec))
    return rules
