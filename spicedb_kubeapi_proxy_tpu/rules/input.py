"""ResolveInput: the per-request data rules evaluate against.

Mirrors the reference's input model (rules.go:231-348): name/namespace
normalization (object metadata preferred, namespace cleared for the
``namespaces`` resource), and the two evaluation data shapes — the template
data map (Bloblang shape, rules.go:521-614: body merged with object
metadata, ``resourceId`` alias) and the condition data map (CEL shape,
rules.go:467-518: ``resourceNamespace`` instead of ``namespace``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class RequestInfo:
    """Parsed kube request metadata (k8s.io/apiserver request.RequestInfo)."""

    verb: str = ""
    api_group: str = ""
    api_version: str = ""
    resource: str = ""
    subresource: str = ""
    name: str = ""
    namespace: str = ""
    path: str = ""
    is_resource_request: bool = True
    label_selector: str = ""
    field_selector: str = ""


@dataclass
class UserInfo:
    name: str = ""
    uid: str = ""
    groups: list[str] = field(default_factory=list)
    extra: dict[str, list[str]] = field(default_factory=dict)


@dataclass
class ResolveInput:
    name: str
    namespace: str
    namespaced_name: str
    request: RequestInfo
    user: UserInfo
    object: Optional[dict]  # parsed body object (with metadata), if any
    body: Optional[bytes]
    headers: dict[str, str]

    @staticmethod
    def create(request: RequestInfo, user: UserInfo,
               body: Optional[bytes] = None,
               headers: Optional[dict] = None) -> "ResolveInput":
        obj: Optional[dict] = None
        if body and request.verb in ("create", "update", "patch"):
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    obj = parsed
            except (ValueError, UnicodeDecodeError):
                obj = None
        meta = (obj or {}).get("metadata") or {}
        # object metadata preferred, request fields as fallback
        # (reference rules.go:312-338)
        name = meta.get("name") or request.name
        namespace = meta.get("namespace") or request.namespace
        if request.resource == "namespaces":
            # namespace requests carry the namespace name in both fields;
            # clear it so namespaces look like other cluster-scoped objects
            namespace = ""
        namespaced_name = f"{namespace}/{name}" if namespace else name
        return ResolveInput(
            name=name,
            namespace=namespace,
            namespaced_name=namespaced_name,
            request=request,
            user=user,
            object=obj,
            body=body,
            headers=dict(headers or {}),
        )

    # -- evaluation data shapes ---------------------------------------------

    def _request_map(self) -> dict:
        return {
            "verb": self.request.verb,
            "apiGroup": self.request.api_group,
            "apiVersion": self.request.api_version,
            "resource": self.request.resource,
            "name": self.request.name,
            "namespace": self.request.namespace,
            "path": self.request.path,
            "labelSelector": self.request.label_selector,
            "fieldSelector": self.request.field_selector,
        }

    def _user_map(self) -> dict:
        return {
            "name": self.user.name,
            "uid": self.user.uid,
            "groups": list(self.user.groups),
            "extra": {k: list(v) for k, v in self.user.extra.items()},
        }

    def template_data(self) -> dict[str, Any]:
        """Template/tupleSet evaluation shape (Bloblang input,
        rules.go:521-614)."""
        data: dict[str, Any] = {
            "name": self.name,
            "namespace": self.namespace,
            "namespacedName": self.namespaced_name,
            "resourceId": self.namespaced_name,
            "headers": dict(self.headers),
            "request": self._request_map(),
            "user": self._user_map(),
        }
        if self.object is not None:
            data["object"] = self.object
            if "metadata" in self.object:
                data["metadata"] = self.object["metadata"]
        if self.body:
            try:
                data["body"] = self.body.decode("utf-8")
            except UnicodeDecodeError:
                pass
        return data

    def condition_data(self) -> dict[str, Any]:
        """`if`-condition evaluation shape (CEL input, rules.go:467-518)."""
        data: dict[str, Any] = {
            "name": self.name,
            "resourceNamespace": self.namespace,
            "namespacedName": self.namespaced_name,
            "headers": dict(self.headers),
            "request": self._request_map(),
            "user": self._user_map(),
        }
        if self.object is not None:
            data["object"] = self.object
        if self.body:
            try:
                data["body"] = self.body.decode("utf-8")
            except UnicodeDecodeError:
                pass
        return data
