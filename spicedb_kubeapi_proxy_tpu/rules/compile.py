"""Rule compilation: specs -> runnable rules with precompiled expressions.

Mirrors /root/reference/pkg/rules/rules.go Compile (rules.go:716-897): every
template field becomes a compiled expression at boot (literals wrapped as
literal expressions), tupleSets compile to expressions producing lists of
relationship strings, and `if` conditions compile to boolean programs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..models.tuples import TupleError, parse_rel_fields
from .expr import CompiledExpr, ExprError, compile_expr, compile_template
from .input import ResolveInput
from .proxyrule import (
    PreFilterSpec,
    PostFilterSpec,
    RuleConfig,
    StringOrTemplate,
    UpdateSpec,
)


class CompileError(ValueError):
    pass


@dataclass(frozen=True)
class ResolvedRel:
    resource_type: str
    resource_id: str
    resource_relation: str
    subject_type: str
    subject_id: str
    subject_relation: str = ""

    def __str__(self) -> str:
        s = (f"{self.resource_type}:{self.resource_id}"
             f"#{self.resource_relation}"
             f"@{self.subject_type}:{self.subject_id}")
        if self.subject_relation:
            s += f"#{self.subject_relation}"
        return s


class RelationshipExpr:
    """A compiled expression producing relationships from a ResolveInput
    (reference RelationshipExpr interface, rules.go:148-152)."""

    def generate(self, input: ResolveInput) -> list[ResolvedRel]:
        raise NotImplementedError


@dataclass
class RelExpr(RelationshipExpr):
    """Six compiled field expressions -> exactly one relationship
    (reference RelExpr, rules.go:204-210)."""

    resource_type: CompiledExpr
    resource_id: CompiledExpr
    resource_relation: CompiledExpr
    subject_type: CompiledExpr
    subject_id: CompiledExpr
    subject_relation: Optional[CompiledExpr] = None

    def generate(self, input: ResolveInput) -> list[ResolvedRel]:
        data = input.template_data()
        try:
            rel = ResolvedRel(
                self.resource_type.evaluate_str(data),
                self.resource_id.evaluate_str(data),
                self.resource_relation.evaluate_str(data),
                self.subject_type.evaluate_str(data),
                self.subject_id.evaluate_str(data),
                (self.subject_relation.evaluate_str(data)
                 if self.subject_relation else ""),
            )
        except ExprError as e:
            raise ExprError(f"resolving relationship: {e}") from None
        for f_ in ("resource_type", "resource_id", "resource_relation",
                   "subject_type", "subject_id"):
            if not getattr(rel, f_):
                raise ExprError(f"relationship field {f_} resolved empty")
        return [rel]


@dataclass
class TupleSetExpr(RelationshipExpr):
    """One compiled expression -> a list of relationship strings, each
    parsed into a relationship (reference TupleSetExpr, rules.go:154-201)."""

    expr: CompiledExpr

    def generate(self, input: ResolveInput) -> list[ResolvedRel]:
        data = input.template_data()
        v = self.expr.evaluate(data)
        if not isinstance(v, list):
            raise ExprError(
                f"tupleSet expression must evaluate to a list of relationship "
                f"strings, got {type(v).__name__}")
        out: list[ResolvedRel] = []
        for i, item in enumerate(v):
            if not isinstance(item, str):
                raise ExprError(f"tupleSet item {i} is not a string")
            try:
                f_ = parse_rel_fields(item)
            except TupleError as e:
                raise ExprError(f"tupleSet item {i}: {e}") from None
            out.append(ResolvedRel(
                f_["resource_type"], f_["resource_id"], f_["relation"],
                f_["subject_type"], f_["subject_id"],
                f_["subject_relation"] or "",
            ))
        return out


@dataclass
class PreFilter:
    """LookupResources-based pre-filter (reference rules.go:686-699): the
    rel's resource_id must resolve to `$`; name/namespace expressions map
    each looked-up object id to an allowed (namespace, name)."""

    name_expr: CompiledExpr
    namespace_expr: Optional[CompiledExpr]
    rel: RelExpr

    @property
    def mapping_kind(self) -> str:
        """Classification of the id->(ns, name) mapping so the hot
        prefilter loop can vectorize the dominant forms: "identity"
        ({{resourceId}} name, no namespace expr), "split"
        (split_name/split_namespace pair), or "general" (anything else,
        incl. braceless literals — those have empty refs and mean a
        CONSTANT name, never the id). A property derived from the exprs
        (not stored state) so tests substituting duck-typed expr fakes
        can never leave a stale classification; whitespace inside the
        expression is insignificant ('{{ split_name( resourceId ) }}'
        still vectorizes)."""
        def norm(e) -> Optional[str]:
            if e is None or "resourceId" not in getattr(e, "refs", ()):
                return None
            return "".join(getattr(e, "source", "").split())

        name_src = norm(self.name_expr)
        if name_src == "resourceId" and self.namespace_expr is None:
            return "identity"
        if name_src == "split_name(resourceId)" and \
                norm(self.namespace_expr) == "split_namespace(resourceId)":
            return "split"
        return "general"

    def mapping_shareable(self) -> bool:
        """True when the id→(namespace, name) mapping depends on nothing
        but the looked-up resourceId — then two watchers resolving the
        SAME relationship produce identical allowed sets, and the watch
        hub may compute once and fan out (exprs referencing user/headers/
        request fields disable sharing; over-collected refs only cost the
        optimization, never correctness)."""
        refs = set(self.name_expr.refs)
        if self.namespace_expr is not None:
            refs |= self.namespace_expr.refs
        return refs <= {"resourceId"}


@dataclass
class PostFilter:
    rel: RelationshipExpr


@dataclass
class UpdateSet:
    preconditions_exist: list[RelationshipExpr] = field(default_factory=list)
    preconditions_do_not_exist: list[RelationshipExpr] = field(default_factory=list)
    creates: list[RelationshipExpr] = field(default_factory=list)
    touches: list[RelationshipExpr] = field(default_factory=list)
    deletes: list[RelationshipExpr] = field(default_factory=list)
    delete_by_filter: list[RelationshipExpr] = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.creates or self.touches or self.deletes
                    or self.delete_by_filter)


@dataclass
class RunnableRule:
    """A precompiled rule (reference RunnableRule, rules.go:657-666)."""

    name: str
    locking: str = ""
    ifs: list[CompiledExpr] = field(default_factory=list)
    checks: list[RelationshipExpr] = field(default_factory=list)
    post_checks: list[RelationshipExpr] = field(default_factory=list)
    pre_filters: list[PreFilter] = field(default_factory=list)
    post_filters: list[PostFilter] = field(default_factory=list)
    update: UpdateSet = field(default_factory=UpdateSet)

    def conditions_pass(self, input: ResolveInput) -> bool:
        """All `if` expressions must evaluate true (reference
        EvaluateCELConditions, rules.go:417-464)."""
        if not self.ifs:
            return True
        data = input.condition_data()
        return all(c.evaluate_bool(data) for c in self.ifs)


def _compile_rel_string(tpl: str) -> RelExpr:
    try:
        f_ = parse_rel_fields(tpl)
    except TupleError as e:
        raise CompileError(str(e)) from None
    return RelExpr(
        compile_template(f_["resource_type"]),
        compile_template(f_["resource_id"]),
        compile_template(f_["relation"]),
        compile_template(f_["subject_type"]),
        compile_template(f_["subject_id"]),
        compile_template(f_["subject_relation"]) if f_["subject_relation"] else None,
    )


def _compile_sot(sot: StringOrTemplate) -> RelationshipExpr:
    try:
        if sot.template:
            return _compile_rel_string(sot.template)
        if sot.tuple_set:
            return TupleSetExpr(compile_expr(sot.tuple_set))
        rt = sot.rel_template
        if rt:
            res, sub = rt["resource"], rt["subject"]
            return RelExpr(
                compile_template(str(res.get("type", ""))),
                compile_template(str(res.get("id", ""))),
                compile_template(str(res.get("relation", ""))),
                compile_template(str(sub.get("type", ""))),
                compile_template(str(sub.get("id", ""))),
                (compile_template(str(sub["relation"]))
                 if sub.get("relation") else None),
            )
    except ExprError as e:
        raise CompileError(str(e)) from None
    raise CompileError("empty StringOrTemplate")


def _compile_sot_rel(sot: StringOrTemplate, where: str) -> RelExpr:
    e = _compile_sot(sot)
    if not isinstance(e, RelExpr):
        raise CompileError(f"{where}: tupleSet is not allowed here")
    return e


def _compile_prefilter(p: PreFilterSpec, where: str) -> PreFilter:
    try:
        name_expr = compile_template(p.from_object_id_name_expr)
        ns_expr = (compile_template(p.from_object_id_namespace_expr)
                   if p.from_object_id_namespace_expr else None)
    except ExprError as e:
        raise CompileError(f"{where}: {e}") from None
    rel = _compile_sot_rel(p.lookup_matching_resources, where)
    return PreFilter(name_expr, ns_expr, rel)


def compile_rule(cfg: RuleConfig) -> RunnableRule:
    """Compile one rule config (reference Compile, rules.go:716-897)."""
    s = cfg.spec
    where = f"rule {cfg.name!r}"
    try:
        ifs = [compile_expr(c) for c in s.ifs]
    except ExprError as e:
        raise CompileError(f"{where}: if: {e}") from None
    upd: UpdateSpec = s.update
    return RunnableRule(
        name=cfg.name,
        locking=s.locking,
        ifs=ifs,
        checks=[_compile_sot(c) for c in s.checks],
        post_checks=[_compile_sot(c) for c in s.post_checks],
        pre_filters=[
            _compile_prefilter(p, f"{where}: prefilter") for p in s.pre_filters
        ],
        post_filters=[
            PostFilter(_compile_sot(p.check_permission_template))
            for p in s.post_filters
        ],
        update=UpdateSet(
            preconditions_exist=[
                _compile_sot_rel(x, f"{where}: preconditionExists")
                for x in upd.precondition_exists
            ],
            preconditions_do_not_exist=[
                _compile_sot_rel(x, f"{where}: preconditionDoesNotExist")
                for x in upd.precondition_does_not_exist
            ],
            creates=[_compile_sot(x) for x in upd.creates],
            touches=[_compile_sot(x) for x in upd.touches],
            deletes=[_compile_sot(x) for x in upd.deletes],
            delete_by_filter=[
                _compile_sot_rel(x, f"{where}: deleteByFilter")
                for x in upd.delete_by_filter
            ],
        ),
    )
