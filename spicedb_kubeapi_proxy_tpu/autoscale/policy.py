"""The autoscaler's pure decision kernel.

One call per controller tick: :meth:`AutoscalePolicy.observe` folds the
tick's :class:`Signals` into a direction (grow / shrink / hold) and
returns a :class:`Proposal` only when the direction has held for
``hysteresis_ticks`` CONSECUTIVE ticks and the cooldown since the last
fired proposal has elapsed. Everything here is deterministic and
clock-injected — the controller (and the tests) own time.

Guards, in decision order:

- **in-flight transition**: no proposal while a rebalance is active or
  an archived transition still owes GC (``begin_rebalance`` would
  refuse the shrink anyway — the policy never proposes what the
  planner must reject); the streak RESETS, so post-transition signals
  must re-earn the hysteresis from scratch;
- **grow** (capacity first): occupancy at/above ``grow_occupancy``, OR
  short-window SLO burn at/above ``grow_burn``, OR mean check latency
  at/above ``grow_latency_ms`` (0 disables the latency trigger) —
  bounded by ``max_groups``;
- **never-shrink-while-burning**: a shrink needs occupancy at/below
  ``shrink_occupancy`` AND burn strictly below ``burning_burn`` — an
  error budget burning at or past rate 1.0 means the fleet is already
  failing its objective, and removing capacity would be the controller
  amplifying an outage it exists to prevent — bounded by
  ``min_groups``.

Hysteresis is per-direction: a grow tick followed by a shrink tick
restarts the streak, so signal flapping around a threshold proposes
nothing (the classic thrash the cooldown alone would only slow down).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional


class AutoscaleError(ValueError):
    pass


@dataclass(frozen=True)
class PolicyConfig:
    """Operator knobs (``--autoscale-policy`` key=value CSV)."""

    min_groups: int = 1
    max_groups: int = 8
    grow_occupancy: float = 0.8
    shrink_occupancy: float = 0.3
    grow_burn: float = 2.0
    burning_burn: float = 1.0
    grow_latency_ms: float = 0.0  # 0 disables the latency trigger
    hysteresis_ticks: int = 3
    cooldown_seconds: float = 300.0

    def validate(self) -> "PolicyConfig":
        if not 1 <= self.min_groups <= self.max_groups:
            raise AutoscaleError(
                f"autoscale bounds must satisfy 1 <= min_groups "
                f"({self.min_groups}) <= max_groups "
                f"({self.max_groups})")
        if not 0.0 < self.grow_occupancy <= 1.0:
            raise AutoscaleError(
                f"grow_occupancy {self.grow_occupancy} must be in "
                "(0, 1]")
        if not 0.0 <= self.shrink_occupancy < self.grow_occupancy:
            raise AutoscaleError(
                f"shrink_occupancy {self.shrink_occupancy} must be in "
                f"[0, grow_occupancy={self.grow_occupancy}) — "
                "overlapping bands would thrash")
        if self.grow_burn <= 0 or self.burning_burn <= 0:
            raise AutoscaleError("burn thresholds must be > 0")
        if self.grow_latency_ms < 0:
            raise AutoscaleError("grow_latency_ms must be >= 0")
        if self.hysteresis_ticks < 1:
            raise AutoscaleError("hysteresis_ticks must be >= 1")
        if self.cooldown_seconds < 0:
            raise AutoscaleError("cooldown_seconds must be >= 0")
        return self


_POLICY_FIELDS = {
    "min_groups": int, "max_groups": int,
    "grow_occupancy": float, "shrink_occupancy": float,
    "grow_burn": float, "burning_burn": float,
    "grow_latency_ms": float,
    "hysteresis_ticks": int, "cooldown_seconds": float,
}


def parse_policy(spec: str) -> PolicyConfig:
    """``"max_groups=6,grow_occupancy=0.7"`` -> a validated config
    (unnamed knobs keep their defaults)."""
    kwargs = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key = key.strip()
        conv = _POLICY_FIELDS.get(key)
        if not eq or conv is None:
            raise AutoscaleError(
                f"unknown autoscale policy knob {key!r} (known: "
                f"{', '.join(sorted(_POLICY_FIELDS))})")
        try:
            kwargs[key] = conv(val.strip())
        except ValueError:
            raise AutoscaleError(
                f"bad autoscale policy value {part!r}") from None
    return PolicyConfig(**kwargs).validate()


@dataclass(frozen=True)
class Signals:
    """One tick's observed state (controller-collected or injected)."""

    n_groups: int
    occupancy: float = 0.0     # max over groups, [0, 1]
    burn_rate: float = 0.0     # worst short-window SLO burn
    latency_ms: float = 0.0    # max mean engine check latency
    rebalance_active: bool = False
    gc_pending: bool = False   # an archived transition still owes GC


@dataclass(frozen=True)
class Proposal:
    action: str         # "grow" | "shrink"
    target_groups: int
    reason: str


class AutoscalePolicy:
    """Stateful hysteresis/cooldown wrapper around the pure direction
    function; one instance per controller."""

    def __init__(self, config: PolicyConfig, clock=time.monotonic):
        self.config = config.validate()
        self._clock = clock
        self._streak_action: Optional[str] = None
        self._streak = 0
        self._last_fired: Optional[float] = None

    def _direction(self, s: Signals) -> Optional[tuple]:
        c = self.config
        if s.occupancy >= c.grow_occupancy and s.n_groups < c.max_groups:
            return ("grow", f"occupancy {s.occupancy:.2f} >= "
                            f"{c.grow_occupancy:.2f}")
        if s.burn_rate >= c.grow_burn and s.n_groups < c.max_groups:
            return ("grow", f"SLO burn {s.burn_rate:.2f} >= "
                            f"{c.grow_burn:.2f}")
        if c.grow_latency_ms > 0 and s.latency_ms >= c.grow_latency_ms \
                and s.n_groups < c.max_groups:
            return ("grow", f"check latency {s.latency_ms:.1f}ms >= "
                            f"{c.grow_latency_ms:.1f}ms")
        if s.occupancy <= c.shrink_occupancy \
                and s.burn_rate < c.burning_burn \
                and s.n_groups > c.min_groups:
            return ("shrink", f"occupancy {s.occupancy:.2f} <= "
                              f"{c.shrink_occupancy:.2f}, burn "
                              f"{s.burn_rate:.2f} < "
                              f"{c.burning_burn:.2f}")
        return None

    def observe(self, s: Signals,
                now: Optional[float] = None) -> Optional[Proposal]:
        """Fold one tick; returns a proposal when the hysteresis streak
        completes outside the cooldown, else None."""
        ts = self._clock() if now is None else now
        if s.rebalance_active or s.gc_pending:
            # a transition in flight (or owed GC) owns the group space:
            # post-transition signals must re-earn the streak
            self._streak_action, self._streak = None, 0
            return None
        want = self._direction(s)
        if want is None:
            self._streak_action, self._streak = None, 0
            return None
        action, reason = want
        if action == self._streak_action:
            self._streak += 1
        else:
            self._streak_action, self._streak = action, 1
        if self._streak < self.config.hysteresis_ticks:
            return None
        if self._last_fired is not None \
                and ts - self._last_fired < self.config.cooldown_seconds:
            return None
        self._last_fired = ts
        self._streak_action, self._streak = None, 0
        target = s.n_groups + (1 if action == "grow" else -1)
        return Proposal(action, target, reason)


__all__ = ["AutoscaleError", "AutoscalePolicy", "PolicyConfig",
           "Proposal", "Signals", "parse_policy"]
