"""The autoscale controller: signal collection + (optionally) acting.

A background tick loop around :class:`~.policy.AutoscalePolicy`:

- **collect** — SLO burn from the shared :class:`~..obs.slo.SLOMonitor`
  (worst short-window burn across objectives), per-group admission
  occupancy and mean engine check latency over the ``load_status``
  wire probe (in-process engines in tests have no probe and read 0 —
  tests inject ``signal_fn``), and the planner's transition state;
- **decide** — one ``policy.observe`` per tick; every proposal counts
  in ``autoscale_proposals_total{action=...}`` whether or not it acts;
- **act** — dry-run (the default) stops there, surfacing the latest
  proposal on ``/readyz``; ``mode="apply"`` drives the REAL transition
  through the existing coordinator: a grow appends one group (built by
  the injected ``grow_group_source`` — flag-configured endpoints in
  the proxy, loopback servers in tests), a shrink retires the tail via
  :func:`~..scaleout.rebalance.shrink_map`. Apply outcomes count in
  ``autoscale_transitions_total{action=...,outcome=...}``.

Failure posture: a tick that cannot collect or act logs + counts and
leaves the fleet EXACTLY as it was — the autoscaler is an optimizer,
never a single point of failure; actual transition safety (fail-closed
routing, crash recovery, GC ordering) lives entirely in the rebalance
protocol it drives.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..scaleout.rebalance import shrink_map
from ..scaleout.shardmap import ShardMap
from ..utils.metrics import metrics
from .policy import AutoscalePolicy, Proposal, Signals

log = logging.getLogger("sdbkp.autoscale")


class AutoscaleController:
    """Owns the tick loop; one per planner. ``mode`` is ``"dry-run"``
    or ``"apply"``; ``grow_group_source(index)`` returns
    ``(endpoints, client)`` for a to-be-added group (apply-mode grows
    are refused without one); ``signal_fn()`` overrides collection."""

    def __init__(self, planner, policy: AutoscalePolicy,
                 mode: str = "dry-run",
                 slo_monitor=None, signal_fn=None,
                 grow_group_source=None,
                 tick_seconds: float = 15.0,
                 clock=time.monotonic,
                 coordinator_cfg: Optional[dict] = None):
        if mode not in ("dry-run", "apply"):
            raise ValueError(
                f"autoscale mode must be dry-run or apply, got {mode!r}")
        self.planner = planner
        self.policy = policy
        self.mode = mode
        self.slo_monitor = slo_monitor
        self._signal_fn = signal_fn
        self._grow_source = grow_group_source
        self.tick_seconds = float(tick_seconds)
        self._clock = clock
        self._coordinator_cfg = dict(coordinator_cfg or {})
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._last_proposal: Optional[dict] = None
        self._transitions = 0

    # -- signal collection ---------------------------------------------------

    def collect_signals(self) -> Signals:
        if self._signal_fn is not None:
            return self._signal_fn()
        p = self.planner
        occupancy = 0.0
        latency_ms = 0.0
        for c in list(p.groups):
            if not hasattr(c, "load_status"):
                continue  # in-process engine: no probe, reads 0
            try:
                st = c.load_status() or {}
            except Exception:  # noqa: BLE001 - probe is best-effort
                # an unreachable group is a failover/readiness problem,
                # not a scaling signal — the probe must not turn one
                # flaky host into a fleet-wide grow
                continue
            occupancy = max(occupancy, float(st.get("occupancy") or 0))
            latency_ms = max(latency_ms, float(st.get("check_ms") or 0))
        burn = 0.0
        if self.slo_monitor is not None:
            burn = float(self.slo_monitor.worst_burn())
        return Signals(
            n_groups=len(p.groups),
            occupancy=occupancy,
            burn_rate=burn,
            latency_ms=latency_ms,
            rebalance_active=p.rebalance_status() is not None,
            gc_pending=any(not t.gc_complete
                           for t in p._archived_transitions),
        )

    # -- decide / act --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[Proposal]:
        """One observe-decide-act cycle; returns the proposal (if any)
        so tests drive ticks synchronously."""
        ts = self._clock() if now is None else now
        try:
            signals = self.collect_signals()
        except Exception as e:  # noqa: BLE001 - collection best-effort
            log.warning("autoscale signal collection failed: %s", e)
            metrics.counter("autoscale_tick_errors_total").inc()
            return None
        proposal = self.policy.observe(signals, now=ts)
        if proposal is None:
            return None
        metrics.counter("autoscale_proposals_total",
                        action=proposal.action).inc()
        with self._lock:
            self._last_proposal = {
                "action": proposal.action,
                "target_groups": proposal.target_groups,
                "reason": proposal.reason,
                "mode": self.mode,
            }
        log.warning("autoscale proposal (%s): %s -> %d groups (%s)",
                    self.mode, proposal.action,
                    proposal.target_groups, proposal.reason)
        if self.mode == "apply":
            self._apply(proposal)
        return proposal

    def _apply(self, p: Proposal) -> None:
        try:
            if p.action == "grow":
                gi = len(self.planner.groups)
                if self._grow_source is None:
                    raise RuntimeError(
                        "autoscale apply-mode grow needs a "
                        "grow_group_source (no spare group endpoints "
                        "configured)")
                endpoints, client = self._grow_source(gi)
                old = self.planner.map
                new_map = ShardMap(
                    version=old.version + 1,
                    groups=tuple(old.groups) + (tuple(endpoints),),
                    virtual_nodes=old.virtual_nodes)
                new_clients = {gi: client} if client is not None else None
                self.planner.begin_rebalance(
                    new_map, new_clients=new_clients,
                    **self._coordinator_cfg)
            else:
                new_map = shrink_map(self.planner.map)
                self.planner.begin_rebalance(
                    new_map, **self._coordinator_cfg)
        except Exception as e:  # noqa: BLE001 - act must not kill loop
            metrics.counter("autoscale_transitions_total",
                            action=p.action, outcome="failed").inc()
            log.error("autoscale %s to %d groups failed to start: %s",
                      p.action, p.target_groups, e)
            return
        with self._lock:
            self._transitions += 1
        metrics.counter("autoscale_transitions_total",
                        action=p.action, outcome="started").inc()

    # -- lifecycle / status --------------------------------------------------

    def start(self) -> "AutoscaleController":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.tick_seconds):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - loop must not die
                    metrics.counter("autoscale_tick_errors_total").inc()

        self._thread = threading.Thread(target=loop, name="autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.tick_seconds + 1)

    def status(self) -> dict:
        """The ``/readyz`` ``autoscale:`` info-line document."""
        with self._lock:
            return {
                "mode": self.mode,
                "groups": len(self.planner.groups),
                "transitions": self._transitions,
                "last_proposal": (dict(self._last_proposal)
                                  if self._last_proposal else None),
            }


__all__ = ["AutoscaleController"]
