"""SLO-driven elastic scale-out: grow/shrink proposals + controller.

``policy.py`` is the pure decision kernel — signals in, proposal out,
with hysteresis, cooldown, and the never-shrink-while-burning guard;
``controller.py`` is the background loop that collects the signals
(SLO burn from obs/slo.py, per-group admission occupancy and check
latency over the ``load_status`` wire probe) and, in apply mode,
drives real map transitions through the existing rebalance
coordinator (scaleout/rebalance.py) — a grow appends a group, a
shrink retires the tail through ``shrink_map``. Dry-run is the
default: proposals are counted and surfaced on ``/readyz``, nothing
moves.
"""

from .policy import (
    AutoscaleError,
    AutoscalePolicy,
    PolicyConfig,
    Proposal,
    Signals,
    parse_policy,
)
from .controller import AutoscaleController

__all__ = [
    "AutoscaleController", "AutoscaleError", "AutoscalePolicy",
    "PolicyConfig", "Proposal", "Signals", "parse_policy",
]
