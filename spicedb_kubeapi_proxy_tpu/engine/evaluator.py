"""Pure-Python oracle evaluator: the correctness reference for the TPU path.

Implements Zanzibar check / lookup-resources semantics by direct recursive
expansion over a store snapshot, mirroring what the reference delegates to
SpiceDB's dispatcher (depth-limited to 50 like the embedded server,
/root/reference/pkg/spicedb/spicedb.go:33). Slow and host-only by design —
tests compare ops/reachability.py's vectorized fixpoint against this.
"""

from __future__ import annotations

import time
from typing import Optional

from ..models.schema import (
    Arrow,
    Exclude,
    Intersect,
    Nil,
    Permission,
    RelationRef,
    Schema,
    Union,
)
from .store import Snapshot

MAX_DEPTH = 50

WILDCARD_ID = "*"


class DepthExceeded(Exception):
    pass


class OracleEvaluator:
    def __init__(self, schema: Schema, snapshot: Snapshot,
                 now: Optional[float] = None,
                 context: Optional[dict] = None):
        self.schema = schema
        self.now = time.time() if now is None else now
        # the request's caveat context; merged UNDER each tuple's stored
        # context (tuple wins), with the evaluation clock auto-injected
        # as the `now` parameter — mirroring the VM's semantics
        self.context = dict(context or {})
        # (rtype, rid, relation) -> list[(stype, sid, srel|None, cav id)]
        self.adj: dict[tuple, list[tuple]] = {}
        # type -> live object ids
        self.objects: dict[str, set] = {}
        self._cav_table = getattr(snapshot, "caveat_instances",
                                  None) or [("", "")]
        self._cav_memo: dict[int, Optional[bool]] = {0: True}
        c = snapshot.cols
        types, rels, objs = snapshot.types, snapshot.relations, snapshot.objects
        for i in range(len(c)):
            if c.exp[i] <= self.now:
                continue  # expired tuples are invisible at read time
            rt = types.string(int(c.rt[i]))
            rid = objs[int(c.rt[i])].string(int(c.rid[i]))
            rl = rels.string(int(c.rl[i]))
            st = types.string(int(c.st[i]))
            sid = objs[int(c.st[i])].string(int(c.sid[i]))
            srl = rels.string(int(c.srl[i])) or None
            self.adj.setdefault((rt, rid, rl), []).append(
                (st, sid, srl, int(c.cav[i])))
            self.objects.setdefault(rt, set()).add(rid)

    def _cav_ok(self, cav: int) -> bool:
        """Tri-state caveat verdict for an instance id, collapsed to the
        edge's activation (missing context == False: fail closed).
        Memoized — instances are few and context is fixed per oracle."""
        got = self._cav_memo.get(cav)
        if got is None and cav not in self._cav_memo:
            got = self._eval_caveat(cav)
            self._cav_memo[cav] = got
        return bool(got)

    def _eval_caveat(self, cav: int) -> Optional[bool]:
        import json

        from ..caveats.ast import StringInterner, interpret
        from ..caveats.vm import NOW_PARAM

        name, ctx_json = self._cav_table[cav]
        defn = (getattr(self.schema, "caveat_defs", None) or {}).get(name)
        if defn is None:
            return False  # undeclared: never grant
        params = {p.name: p.type for p in defn.params}
        merged = dict(self.context)
        if NOW_PARAM in params and NOW_PARAM not in merged:
            merged[NOW_PARAM] = self.now
        if ctx_json:
            try:
                merged.update(json.loads(ctx_json))
            except ValueError:
                return None  # unreadable stored context: no verdict
        # one shared interner is enough for the oracle: strings compare
        # by code, and interning everything visible keeps codes aligned
        interner = StringInterner()
        for v in merged.values():
            if isinstance(v, str):
                interner.intern(v)
            elif isinstance(v, list):
                for x in v:
                    if isinstance(x, str):
                        interner.intern(x)
        from ..caveats.ast import CaveatError, Lit, walk

        for node in walk(defn.expr):
            if isinstance(node, Lit):
                if node.type == "string":
                    interner.intern(node.value)
                elif node.type == "list":
                    for x in node.value:
                        if isinstance(x, str):
                            interner.intern(x)
        try:
            return interpret(defn.expr, merged, params, interner)
        except CaveatError:
            return None  # unencodable context: no verdict, fail closed

    # -- public ------------------------------------------------------------

    def check(
        self,
        resource_type: str,
        resource_id: str,
        permission: str,
        subject_type: str,
        subject_id: str,
        subject_relation: Optional[str] = None,
    ) -> bool:
        subject = (subject_type, subject_id, subject_relation)
        memo: dict[tuple, bool] = {}
        return self._eval(resource_type, resource_id, permission, subject,
                          memo, frozenset(), 0)

    def lookup_resources(
        self,
        resource_type: str,
        permission: str,
        subject_type: str,
        subject_id: str,
        subject_relation: Optional[str] = None,
    ) -> set:
        subject = (subject_type, subject_id, subject_relation)
        memo: dict[tuple, bool] = {}
        out = set()
        for rid in self.objects.get(resource_type, ()):  # only ids in the graph
            if self._eval(resource_type, rid, permission, subject, memo,
                          frozenset(), 0):
                out.add(rid)
        return out

    # -- internals ----------------------------------------------------------

    def _eval(self, rtype, rid, relname, subject, memo, path, depth) -> bool:
        if depth > MAX_DEPTH:
            raise DepthExceeded(f"{rtype}:{rid}#{relname}")
        # Zanzibar identity: a userset is a member of itself —
        # check(g:eng#member @ g:eng#member) is true (matches the device
        # path, which seeds the subject's own userset slot).
        if subject[2] is not None and (rtype, rid, relname) == subject:
            return True
        key = (rtype, rid, relname)
        if key in memo:
            return memo[key]
        if key in path:
            return False  # cycle: contributes nothing new (least fixpoint)
        d = self.schema.definitions.get(rtype)
        if d is None:
            return False
        path = path | {key}
        if relname in d.relations:
            res = self._eval_relation(rtype, rid, relname, subject, memo, path, depth)
        elif relname in d.permissions:
            res = self._eval_expr(d.permissions[relname].expr, rtype, rid,
                                  subject, memo, path, depth)
        else:
            res = False
        # Only completed True results are safe to memoize: a False may be an
        # artifact of a cycle cut on this particular path.
        if res:
            memo[key] = res
        return res

    def _eval_relation(self, rtype, rid, relname, subject, memo, path, depth) -> bool:
        stype_q, sid_q, srel_q = subject
        for st, sid, srl, cav in self.adj.get((rtype, rid, relname), ()):
            if not self._cav_ok(cav):
                continue  # conditional grant not satisfied: edge is off
            if srl is None:
                if st == stype_q and srel_q is None and (
                    sid == sid_q or sid == WILDCARD_ID
                ):
                    return True
                # a userset subject query matches nothing concrete
            else:
                # exact userset match (subject itself is that userset)
                if (st, sid, srl) == (stype_q, sid_q, srel_q):
                    return True
                if self._eval(st, sid, srl, subject, memo, path, depth + 1):
                    return True
        return False

    def _eval_expr(self, expr, rtype, rid, subject, memo, path, depth) -> bool:
        if isinstance(expr, Nil):
            return False
        if isinstance(expr, RelationRef):
            return self._eval(rtype, rid, expr.name, subject, memo, path, depth + 1)
        if isinstance(expr, Union):
            return any(self._eval_expr(e, rtype, rid, subject, memo, path, depth)
                       for e in expr.operands)
        if isinstance(expr, Intersect):
            return all(self._eval_expr(e, rtype, rid, subject, memo, path, depth)
                       for e in expr.operands)
        if isinstance(expr, Exclude):
            return self._eval_expr(expr.base, rtype, rid, subject, memo, path, depth) \
                and not self._eval_expr(expr.subtract, rtype, rid, subject, memo,
                                        path, depth)
        if isinstance(expr, Arrow):
            for st, sid, srl, cav in self.adj.get(
                    (rtype, rid, expr.tupleset), ()):
                if srl is not None or sid == WILDCARD_ID:
                    continue  # arrows walk concrete subjects only
                if not self._cav_ok(cav):
                    continue  # conditional tupleset edge not satisfied
                sub_def = self.schema.definitions.get(st)
                if sub_def and sub_def.relation_or_permission(expr.target):
                    if self._eval(st, sid, expr.target, subject, memo, path,
                                  depth + 1):
                        return True
            return False
        raise TypeError(f"unknown expr node {expr!r}")
