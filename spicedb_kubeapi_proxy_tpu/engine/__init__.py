"""The relationship-graph engine — the embedded-SpiceDB replacement.

Host side: string interning, a mutable columnar relationship store with
revisions/preconditions/watch (reference pkg/spicedb embedded server
semantics), and a pure-Python oracle evaluator used as the correctness
oracle for the TPU path. Device side: snapshots compiled by
ops/reachability.py and queried through :class:`Engine`.
"""

from .interning import Interner  # noqa: F401
from .store import (  # noqa: F401
    Columns,
    Precondition,
    PreconditionFailed,
    RelationshipFilter,
    Store,
    StoreError,
    WriteOp,
)
from .decision_cache import DecisionCache  # noqa: F401
from .evaluator import OracleEvaluator  # noqa: F401
from .engine import CheckItem, Engine, WatchEvent  # noqa: F401
