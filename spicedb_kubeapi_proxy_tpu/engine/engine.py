"""The query engine: SpiceDB-equivalent API over the TPU reachability path.

Public surface mirrors what the reference proxy consumes from authzed-go
(SURVEY.md §2.5): WriteRelationships (create/touch/delete + preconditions),
ReadRelationships, DeleteRelationships(filter), CheckPermission /
CheckBulkPermissions, LookupResources, and Watch. All queries are fully
consistent — the reference always requests full consistency
(/root/reference/pkg/authz/check.go:42-44, lookups.go:50-52) — implemented
as compile-on-demand: a query against a stale snapshot recompiles first.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from ..models.bootstrap import Bootstrap, DEFAULT_BOOTSTRAP, parse_bootstrap
from ..models.schema import Schema
from ..models.tuples import Relationship
from ..obs.profile import install_jax_compile_hook
from ..obs.trace import tracer
from ..ops import semiring
from ..ops.reachability import (
    CompiledGraph,
    DELTA_CAPACITY,
    MAX_DELTA_RECORDS,
    _fallback,
    compile_graph,
    incremental_update,
)
from ..utils.metrics import metrics
from .decision_cache import DecisionCache, MISS, check_key, lookup_key
from .evaluator import OracleEvaluator
from .store import (
    Precondition,
    RelationshipFilter,
    Store,
    StoreError,
    WatchRecord,
    WriteOp,
)


class SchemaViolation(StoreError):
    pass


@dataclass(frozen=True)
class CheckItem:
    resource_type: str
    resource_id: str
    permission: str
    subject_type: str
    subject_id: str
    subject_relation: Optional[str] = None


@dataclass(frozen=True)
class WatchEvent:
    revision: int
    operation: str  # "touch" | "delete"
    relationship: Relationship


def context_digest(context) -> Optional[str]:
    """Stable digest of a request caveat-context dict, appended to
    decision-cache keys so conditional verdicts never leak across
    contexts. ``None`` for no/empty context — context-free queries keep
    today's cache keys byte-identical."""
    if not context:
        return None
    import hashlib
    import json

    try:
        blob = json.dumps(context, sort_keys=True,
                          separators=(",", ":"), default=str)
    except (TypeError, ValueError):
        blob = repr(sorted((str(k), str(v)) for k, v in context.items()))
    return hashlib.sha1(blob.encode()).hexdigest()


def mask_to_ids(mask, interner) -> list:
    """Materialize allowed id strings from a lookup mask: the ONE place
    the padded-index guard lives (padding indices can never be true — no
    edges — but the interner bound is guarded anyway). Shared by the
    in-process, remote, and multi-host lookup paths."""
    if mask is None:
        return []
    return [interner.string(i) for i in np.flatnonzero(mask).tolist()
            if i < len(interner)]


def mask_pseudo_objects(mask: np.ndarray) -> np.ndarray:
    """Clear the reserved per-type pseudo-object indices (0 = void,
    1 = the wildcard object '*') from a lookup mask — shared by the direct
    and batched lookup paths so the slot layout lives in one place."""
    mask[0] = False
    mask[1] = False
    return mask


def validate_caveat(schema: Schema, rel: Relationship) -> None:
    """A caveated write must name a DECLARED caveat and carry a
    context that encodes under the declared parameter types — a
    malformed context stored now would become missing-context
    denials (or a recompile-time error) at read time. Module-level so
    the schema migrator can re-validate stored tuples against a
    CANDIDATE schema without mutating any engine."""
    from ..caveats.ast import (
        CaveatError,
        StringInterner,
        UnencodableListError,
        encode_list,
        encode_scalar,
    )

    cdef = (schema.caveat_defs or {}).get(rel.caveat)
    if cdef is None:
        raise SchemaViolation(
            f"relationship names undeclared caveat {rel.caveat!r}")
    if not rel.caveat_context:
        return
    try:
        ctx = rel.context_dict()
    except ValueError as e:
        raise SchemaViolation(
            f"caveat {rel.caveat!r}: invalid context: {e}") from None
    scratch = StringInterner()
    for k, v in (ctx or {}).items():
        p = cdef.param(k)
        if p is None:
            raise SchemaViolation(
                f"caveat {rel.caveat!r} has no parameter {k!r}")
        try:
            if p.type.is_list:
                encode_list(v, p.type.elem, scratch)
            else:
                encode_scalar(v, p.type.name, scratch)
        except UnencodableListError:
            # well-typed but beyond the VM's list tables (an IPv6
            # element): the write is accepted — the parameter
            # resolves UNKNOWN at evaluation (fail closed, counted)
            pass
        except CaveatError as e:
            raise SchemaViolation(
                f"caveat {rel.caveat!r} context {k!r}: {e}") from None


def validate_relationship(schema: Schema, rel: Relationship) -> None:
    """Schema admission for one relationship tuple — the write path's
    gate, factored to take the schema EXPLICITLY so the migrator can ask
    "does every stored tuple still parse under S'?" before it commits to
    a transition."""
    if getattr(rel, "caveat", None):
        validate_caveat(schema, rel)
    d = schema.definitions.get(rel.resource_type)
    if d is None:
        raise SchemaViolation(f"unknown resource type {rel.resource_type!r}")
    if rel.resource_id == "*":
        # SpiceDB forbids wildcard resource ids; only subjects may be '*'
        raise SchemaViolation("resource id may not be the wildcard '*'")
    r = d.relations.get(rel.relation)
    if r is None:
        raise SchemaViolation(
            f"{rel.resource_type} has no relation {rel.relation!r}"
            + (" (permissions are not writable)"
               if rel.relation in d.permissions else "")
        )
    sub_def = schema.definitions.get(rel.subject_type)
    if sub_def is None:
        raise SchemaViolation(f"unknown subject type {rel.subject_type!r}")
    ok = False
    expiration_blocked = False
    caveat_blocked = False
    for a in r.allowed:
        if a.type != rel.subject_type:
            continue
        if rel.subject_id == "*":
            if not a.wildcard:
                continue
        elif a.wildcard or (a.relation or None) != rel.subject_relation:
            continue
        if (a.caveat or None) != (rel.caveat or None):
            # SpiceDB matches the caveat trait exactly: a caveated
            # tuple needs a `with <caveat>` entry, and an entry
            # REQUIRING a caveat never accepts an unconditional
            # tuple — another entry of the same subject type may
            # still match (`user | user with ip_allowlist`)
            caveat_blocked = True
            continue
        if rel.expiration is not None and not a.expiration:
            # another allowed entry of the same subject type may carry
            # the expiration trait (e.g. `user | user with expiration`)
            # — keep scanning instead of rejecting on the first match
            expiration_blocked = True
            continue
        ok = True
        break
    if not ok and expiration_blocked:
        raise SchemaViolation(
            f"{rel.resource_type}#{rel.relation} does not allow "
            "expiring relationships"
        )
    if not ok and caveat_blocked:
        raise SchemaViolation(
            f"{rel.resource_type}#{rel.relation} does not allow "
            + (f"subjects with caveat {rel.caveat!r}" if rel.caveat
               else "uncaveated subjects of this type")
        )
    if not ok:
        raise SchemaViolation(
            f"subject {rel.subject_type}"
            + (f"#{rel.subject_relation}" if rel.subject_relation else "")
            + f" not allowed on {rel.resource_type}#{rel.relation}"
        )
    if rel.subject_relation:
        if not schema.definitions[rel.subject_type].relation_or_permission(
            rel.subject_relation
        ):
            raise SchemaViolation(
                f"{rel.subject_type} has no relation "
                f"{rel.subject_relation!r}"
            )


class EngineFuture:
    """A dispatched engine query: ``result()`` blocks and post-processes.
    ``fut`` is a :class:`~...ops.reachability.QueryFuture` or ``None`` for
    trivially-resolved queries; multi-dispatch paths (chunked bulk checks)
    pass ``fut=None`` plus an ``iters`` callable joining their futures."""

    __slots__ = ("_fut", "_fin", "_iters")

    def __init__(self, fut, fin, iters=None):
        self._fut = fut
        self._fin = fin
        self._iters = iters

    def result(self):
        return self._fin(None if self._fut is None else self._fut.result())

    def iterations(self) -> int:
        """Fixpoint hops the query ran (dispatch-depth analog); valid
        after ``result()``."""
        if self._iters is not None:
            return self._iters()
        return 0 if self._fut is None else self._fut.iterations()


class Engine:
    """In-process relationship-graph engine (the ``embedded://`` / ``tpu://``
    backend). Thread-safe."""

    def __init__(self, bootstrap: Optional[str] = None,
                 schema: Optional[Schema] = None,
                 validate_writes: bool = True,
                 mesh=None, delta_capacity: int = DELTA_CAPACITY,
                 device_graph_budget_bytes: Optional[int] = None,
                 tier_spill_dir: Optional[str] = None):
        if schema is None:
            b: Bootstrap = parse_bootstrap(bootstrap or DEFAULT_BOOTSTRAP)
            schema = b.schema
            seed = b.relationships
        else:
            seed = []
        self.schema = schema
        self.store = Store()
        self.validate_writes = validate_writes
        self._lock = threading.RLock()
        self._compiled: Optional[CompiledGraph] = None
        self._batcher = None
        self._decision_cache: Optional[DecisionCache] = None
        self._persistence = None  # persistence/manager.py, opt-in
        # delta-overlay sizing for every graph this engine compiles, and
        # the optional background compactor (engine/compaction.py) that
        # folds the overlay into a fresh base off the write path
        self._delta_capacity = max(int(delta_capacity), 64)
        self._compactor = None
        # tiered graph storage (--device-graph-budget-bytes, storage/):
        # when set, every graph this engine compiles gets its dense
        # blocks residency-tracked under this device byte budget — cold
        # blocks live in host arenas and stream in on demand. 0/None =
        # classic all-resident placement.
        self._tier_budget = int(device_graph_budget_bytes or 0)
        self._tier_spill_dir = tier_spill_dir
        # (base revision, store revision) pair the incremental path
        # declined at write time — the read path must not retry (and
        # re-count) the identical suffix; any further write resets it
        self._incremental_declined: Optional[tuple] = None
        # host-side (q_slots, q_batch) arrays per (offset, size): a mask
        # lookup's query arrays are a pure function of the slot layout, so
        # rebuilding 2x400KB of arange/zeros per request is waste (their
        # DEVICE copies are already cached per key in query_async)
        self._q_host: dict[tuple, tuple] = {}
        # frontier-occupancy EWMA feeding the semiring push/pull
        # crossover: every mask-lookup readback contributes its observed
        # final-frontier fill fraction (the engine_frontier_occupancy
        # signal), and the resulting threshold rides each dispatch as a
        # TRACED scalar — retuning it never recompiles
        self._occ_ewma: Optional[float] = None
        # optional jax.sharding.Mesh ("data", "graph" axes): queries route
        # through a ShardedGraph pinned across it instead of one device
        self.mesh = mesh
        self._sharded = None
        # live schema migration (migration/migrator.py): the active
        # SchemaMigrator, the brief-freeze write gate it installs for
        # the atomic cutover, and the set of backfill-echo revisions
        # watch streams must suppress (a journaled backfill TOUCH of
        # identical content still logs a WatchRecord; replaying it to
        # watchers would break exactly-once across the cut)
        self._migrator = None
        self._write_gate = None
        self._watch_suppress: frozenset = frozenset()
        # XLA compilation is the engine's biggest latency cliff and the
        # one event it cannot time itself; the jax monitoring listener
        # mirrors compile events into the metrics registry (obs/profile)
        install_jax_compile_hook()
        if seed:
            self.write_relationships([WriteOp("touch", r) for r in seed])

    def enable_lookup_batching(self, window: float = 0.002,
                               max_rows: int = 8) -> None:
        """Coalesce concurrent lookup_resources_mask calls into fused
        device dispatches (engine/batcher.py) — trades up to ``window``
        seconds of added latency for one dispatch per ``max_rows``
        concurrent list prefilters."""
        from .batcher import LookupBatcher

        self._batcher = LookupBatcher(self, window=window, max_rows=max_rows)

    def disable_lookup_batching(self) -> None:
        """Revert to one device dispatch per lookup. The retired batcher
        is closed: its pending batch flushes, and any racing submit that
        still holds a reference falls through to the direct engine path
        instead of queueing into a dead batcher."""
        b, self._batcher = self._batcher, None
        if b is not None:
            b.close()

    def enable_decision_cache(self, max_entries: int = 65536,
                              max_mask_bytes: int = 256 << 20) -> None:
        """Serve byte-identical repeat queries at an unchanged store
        revision from a revision-keyed LRU instead of re-dispatching, and
        coalesce concurrent identical misses into one dispatch
        (engine/decision_cache.py). Semantics are unchanged: writes bump
        the revision (new keys), expiring tuples bound every entry with
        the store's next-expiry watermark, and explicit-``now`` queries
        bypass the cache entirely."""
        self._decision_cache = DecisionCache(max_entries=max_entries,
                                             max_mask_bytes=max_mask_bytes)

    def disable_decision_cache(self) -> None:
        """Drop the cache (gauges zeroed); in-flight fills resolve but
        are no longer consulted."""
        c, self._decision_cache = self._decision_cache, None
        if c is not None:
            c.clear()

    def enable_persistence(self, data_dir: str, **kw):
        """Make the relationship store durable under ``data_dir``
        (``--data-dir``): recover whatever a previous process left there
        (newest valid snapshot + WAL tail, persistence/recovery.py), then
        journal every subsequent mutation through a write-ahead log with
        background snapshot checkpoints. Returns the
        :class:`~..persistence.Persistence` manager (its ``.recovery``
        says what was restored). Keyword args pass through to
        ``Persistence.open`` (wal_fsync, checkpoint thresholds...)."""
        from ..persistence import Persistence

        with self._lock:
            if self._persistence is not None:
                raise RuntimeError("persistence is already enabled")
            p = Persistence.open(self.store, data_dir, **kw)
            self._persistence = p
            self._compiled = None  # recovery replaced the store contents
        return p

    def close_persistence(self, final_checkpoint: bool = True) -> None:
        """Graceful shutdown of the durability layer (fsync + by default
        a final checkpoint so the next boot replays nothing)."""
        with self._lock:
            p, self._persistence = self._persistence, None
        if p is not None:
            p.close(final_checkpoint=final_checkpoint)

    @property
    def persistence(self):
        return self._persistence

    def enable_compaction(self, threshold: float = 0.75,
                          delta_capacity: Optional[int] = None):
        """Start the background overlay compactor (engine/compaction.py):
        a worker thread folds the accumulated delta tail into a fresh
        double-buffered compiled base off the write path and swaps it
        atomically at a recorded revision, and the write path sheds with
        a bounded Retry-After (:class:`~.compaction.OverlayBackpressure`)
        instead of letting overlay overflow force a synchronous full
        recompile onto the next fully-consistent read. ``threshold`` is
        the overlay-occupancy fraction that wakes the worker;
        ``delta_capacity`` resizes the overlay for graphs compiled from
        now on (``--delta-capacity``)."""
        from .compaction import Compactor

        with self._lock:
            if self._compactor is not None:
                raise RuntimeError("compaction is already enabled")
            if delta_capacity is not None:
                self._delta_capacity = max(int(delta_capacity), 64)
            self._compactor = Compactor(self, threshold)
        return self._compactor

    def close_compaction(self, drain: bool = False) -> None:
        """Stop the compactor worker (``drain=True`` folds once more
        first); writes stop shedding and overlay overflow reverts to the
        synchronous-recompile fallback."""
        with self._lock:
            c, self._compactor = self._compactor, None
        if c is not None:
            c.close(drain=drain)

    @property
    def compactor(self):
        return self._compactor

    # -- write path ---------------------------------------------------------

    def _validate_caveat(self, rel: Relationship) -> None:
        validate_caveat(self.schema, rel)

    def _validate(self, rel: Relationship) -> None:
        validate_relationship(self.schema, rel)

    def write_relationships(self, ops: list[WriteOp],
                            preconditions: list[Precondition] = (),
                            *, _headroom: bool = True) -> int:
        if self.validate_writes:
            for op in ops:
                self._validate(op.rel)
        if _headroom:
            self._write_headroom(len(ops))
        gate = self._write_gate
        if gate is not None:
            gate.enter()
        try:
            rev = self.store.write(list(ops), list(preconditions))
            self._advance_incremental()
        finally:
            if gate is not None:
                gate.exit()
        return rev

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list[Precondition] = (),
                             *, _headroom: bool = True) -> int:
        # filter cardinality is unknown pre-scan: charge one record's
        # headroom (deletes mostly reuse overlay slots / the dead ledger;
        # a huge filter delete overflowing the ledger still falls back to
        # a counted full recompile, it just isn't shed preemptively)
        if _headroom:
            self._write_headroom(1)
        gate = self._write_gate
        if gate is not None:
            gate.enter()
        try:
            n = self.store.delete_by_filter(f, list(preconditions))
            self._advance_incremental()
        finally:
            if gate is not None:
                gate.exit()
        return n

    def _write_headroom(self, n_records: int) -> None:
        """Back-pressure gate run BEFORE any store mutation: when the
        compactor is enabled and the current overlay cannot absorb the
        write, shed with :class:`~.compaction.OverlayBackpressure`
        (bounded Retry-After) instead of letting the next read pay a
        synchronous full recompile. A shed write leaves no trace —
        nothing journaled, replicated, or applied — so retrying is always
        safe."""
        c = self._compactor
        if c is not None:
            c.check_headroom(self._compiled, n_records)

    def _advance_incremental(self) -> None:
        """Eagerly fold the write just applied into the compiled graph —
        an O(write) overlay append — so the write path itself keeps the
        graph current and the next fully-consistent read dispatches
        immediately. Never compiles: when the incremental path declines
        (layout growth, stratification inversion, overflow), the decline
        is counted and the read path's fallback recompile — or the
        background compactor, when enabled — picks it up."""
        with self._lock:
            cur = self._compiled
            if cur is None or cur.revision == self.store.revision:
                return
            inc = self._try_incremental(cur)
            if inc is not None:
                self._compiled = inc
                self._publish_graph_gauges(inc)
                c = self._compactor
                if c is not None:
                    c.notify(inc)
            else:
                # remember the exact (base, store) revision pair that
                # declined: the read path retrying the same suffix would
                # re-run the whole planning scan, fail identically, and
                # double-count the fallback reason
                self._incremental_declined = (cur.revision,
                                              self.store.revision)
                if self._compactor is not None:
                    # the overlay could not express this write: fold in
                    # the background so the serving path meets a fresh
                    # base instead of recompiling synchronously
                    self._compactor.request()

    def read_relationships(self, f: RelationshipFilter) -> Iterator[Relationship]:
        return self.store.read(f)

    def bulk_load(self, rels_cols: dict) -> int:
        if self.validate_writes and rels_cols.get("caveat") is not None:
            # validate the DISTINCT (caveat, context) pairs before any
            # store mutation: an undeclared name or a type-mismatched
            # context interned here would not fail until the next
            # compile_graph — bricking every subsequent query instead
            # of rejecting one bad load (the write path rejects the
            # same row cleanly via _validate_caveat)
            from ..models.tuples import canonical_context

            names = np.asarray(rels_cols["caveat"], dtype=str)
            ctx_col = rels_cols.get("caveat_context")
            ctxs = (np.asarray(ctx_col, dtype=str)
                    if ctx_col is not None
                    else np.full(len(names), "", dtype=str))
            seen: set = set()
            for nm, cx in zip(names.tolist(), ctxs.tolist()):
                if not nm or (nm, cx) in seen:
                    continue
                seen.add((nm, cx))
                self._validate_caveat(Relationship(
                    "", "", "", "", "", None, None, nm,
                    canonical_context(cx)))
        return self.store.bulk_load(rels_cols)

    # -- query path ---------------------------------------------------------

    def _objects_by_name(self) -> dict:
        # snapshot under the store lock: writers intern new types into
        # store.objects and a concurrent iteration would race
        with self.store._lock:
            return {
                self.store.types.string(tid): it
                for tid, it in self.store.objects.items()
            }

    def _publish_graph_gauges(self, cg: CompiledGraph) -> None:
        # TrieJax-style kernel accounting: the compiled graph's shape
        # gauges let a scrape correlate latency with graph scale (CSR
        # nnz = adjacency edges, M = slot space). Called only when the
        # graph CHANGED — compiled() itself is per-dispatch hot path
        metrics.gauge("engine_csr_nnz").set(cg.n_edges)
        metrics.gauge("engine_graph_slots").set(cg.M)
        metrics.gauge("engine_delta_occupancy").set(cg.n_delta)
        if cg.tier is not None:
            cg.tier.publish_gauges()

    def compiled(self) -> CompiledGraph:
        """Fully-consistent snapshot: a stale compiled graph is brought
        current by an O(delta) incremental update (small writes — the
        dual-write hot path) or a full recompile (bulk loads, schema-shaped
        changes, oversized deltas)."""
        with self._lock:
            cur = self._compiled
            if cur is not None and cur.revision != self.store.revision \
                    and (cur.revision, self.store.revision) \
                    != self._incremental_declined:
                inc = self._try_incremental(cur)
                if inc is not None:
                    self._compiled = inc
                    self._publish_graph_gauges(inc)
                    c = self._compactor
                    if c is not None:
                        c.notify(inc)
                    return inc
            if self._compiled is None or \
               self._compiled.revision != self.store.revision:
                self._compiled = self._compile_fresh()
                self._publish_graph_gauges(self._compiled)
            return self._compiled

    def _compile_fresh(self) -> CompiledGraph:
        """One full compile from the current store snapshot — shared by
        the serving-path fallback (under the engine lock) and the
        background compactor's fold (deliberately OFF the lock: the old
        base keeps serving while the fold runs)."""
        t0 = time.perf_counter()
        cg = compile_graph(self.schema, self.store.snapshot(),
                           delta_capacity=self._delta_capacity)
        if self._tier_budget:
            # each compiled base gets a fresh TierStore: residency and
            # overlay pins start clean, which is exactly the "pinned
            # until folded" rule
            cg.enable_tiering(self._tier_budget,
                              spill_dir=self._tier_spill_dir)
        metrics.counter("engine_graph_compiles_total").inc()
        metrics.histogram("engine_graph_compile_seconds").observe(
            time.perf_counter() - t0)
        return cg

    def _replay_onto(self, fresh: CompiledGraph
                     ) -> Optional[CompiledGraph]:
        """Bring a freshly-compiled base current with the watch-log
        records that landed after its snapshot was cut (the compactor's
        catch-up replay, run under the engine lock so no further write
        can race the swap). Returns the advanced graph, ``fresh`` itself
        when nothing landed, or ``None`` when the suffix cannot be
        replayed incrementally (trimmed history, bulk load, overflow) —
        the caller re-folds from a newer snapshot."""
        st = self.store
        with st._lock:
            if fresh.revision < st.unlogged_revision:
                return None
            try:
                records = st.watch_since(fresh.revision)
            except StoreError:
                return None
            rev = st.revision
        if not records:
            return fresh
        if len(records) > MAX_DELTA_RECORDS:
            return None
        from .store import OP_DELETE

        delta = [(r.op == OP_DELETE, r.rel) for r in records]
        return incremental_update(fresh, delta, rev, st)

    def _try_incremental(self, cur: CompiledGraph) -> Optional[CompiledGraph]:
        from ..utils.features import features

        if not features.enabled("IncrementalGraphUpdates"):
            return None
        st = self.store
        with st._lock:
            if cur.revision < st.unlogged_revision:
                # bulk-loaded/restored changes aren't in the log
                _fallback("unlogged")
                return None
            try:
                records = st.watch_since(cur.revision)
            except StoreError:
                _fallback("history-trimmed")
                return None
            rev = st.revision
        if len(records) > MAX_DELTA_RECORDS:
            _fallback("overflow")
            return None
        t0 = time.perf_counter()
        from .store import OP_DELETE

        delta = [(r.op == OP_DELETE, r.rel) for r in records]
        new = incremental_update(cur, delta, rev, st)
        if new is not None:
            metrics.counter("engine_graph_incremental_updates_total").inc()
            metrics.histogram("engine_graph_incremental_seconds").observe(
                time.perf_counter() - t0)
        return new

    def check(self, item: CheckItem, now: Optional[float] = None,
              context: Optional[dict] = None) -> bool:
        return self.check_bulk([item], now=now, context=context)[0]

    def _cache_deadline(self, cg: CompiledGraph, now0: float,
                        context: Optional[dict]) -> float:
        """Validity horizon for a decision-cache entry filled at
        ``now0``: the store's next expiration boundary joined with the
        caveat table's next verdict-flip instant (time-window caveats
        revoke/grant without a write, exactly like tuple expiry)."""
        deadline = self.store.next_expiry(now0)
        cav = cg.caveats
        if cav is not None and cav.metas:
            deadline = min(deadline, cav.next_time_bound(
                now0, cav.request_ts(context)))
        return deadline

    def watch_gate(self, resource_type: str, name: str
                   ) -> tuple[frozenset, bool]:
        """(relevant types, reachable expiration) for watch streams:
        the types whose writes can affect ``resource_type#name``
        (models/schema.py watch_relevance), and whether a relation the
        watched permission can reach allows expiring tuples — watches skip
        allowed-set recomputes on unrelated write traffic, and only tick
        periodically for expiry when the WATCHED permission (not just the
        schema somewhere) can actually lose grants to the clock."""
        from ..models.schema import watch_relevance

        return watch_relevance(self.schema, resource_type, name)

    def check_bulk(self, items: list[CheckItem],
                   now: Optional[float] = None,
                   context: Optional[dict] = None) -> list[bool]:
        """CheckBulkPermissions: evaluate all items in one device pass,
        batching distinct subjects along B (reference check.go:22-48 issues
        one bulk RPC per request; here the whole bulk is one fixpoint).
        ``context`` is the request's caveat context (client IP, caller
        attributes...) gating conditional grants; the dispatch clock is
        auto-injected as the ``now`` caveat parameter."""
        return self.check_bulk_async(items, now=now,
                                     context=context).result()

    def try_cached_check(self, items: list[CheckItem],
                         context: Optional[dict] = None
                         ) -> Optional[list[bool]]:
        """Non-blocking decision-cache probe: the full verdict list when
        EVERY item is a hit at the current revision, else ``None``
        (a partial answer is useless to the authz chain — it would
        dispatch anyway). Never compiles, never dispatches, never blocks
        beyond a shard lock: callers on an event loop can probe before
        paying the ``asyncio.to_thread`` handoff
        (authz/middleware.py)."""
        cache = self._decision_cache
        if cache is None:
            return None
        if not items:
            return []
        rev = self.store.revision
        # digest-free keys for caveat-less graphs, parameter-scoped
        # digests otherwise (see check_bulk_async) — but ONLY when the
        # current compiled graph provably matches this revision; when
        # unsure, digesting the full context is merely a cache miss,
        # never a wrong answer
        cg = self._compiled
        if cg is not None and cg.revision == rev:
            digest = (context_digest(
                cg.caveats.relevant_context(context))
                if cg.caveats is not None and cg.caveats.metas
                else None)
        else:
            digest = context_digest(context)
        now = time.time()
        out: list[bool] = []
        for it in items:
            v = cache.get(check_key(rev, it, digest), now, record=False)
            if v is MISS:
                return None
            out.append(v)
        # counted only once the WHOLE probe served (partial probes fall
        # through to check_bulk_async, which records its own hits/misses)
        cache.note_hits("check", len(out))
        return out

    def _backend(self, cg: CompiledGraph):
        """The query executor for a compiled graph: the graph itself
        (single device) or a mesh-pinned ShardedGraph, rebuilt whenever the
        compiled graph changes revision. Both expose the same
        ``query_async(seeds, q_slots, q_batch, now)`` surface."""
        if self.mesh is None:
            return cg
        t = cg.tier
        if t is not None and t.total_bytes() > t.budget_bytes:
            # beyond-budget tiered graph: the mesh backend pins every
            # block resident (parallel/sharded.py streams nothing), so
            # a graph that cannot fit routes through the single-chip
            # demand-streaming path instead — counted so a mesh
            # deployment sees why its mesh idles on oversized groups
            metrics.counter("engine_tier_mesh_fallback_total").inc()
            return cg
        from ..parallel.sharded import ShardedGraph

        reason = ShardedGraph.unsupported_reason(cg)
        if reason is not None:
            # caveats evaluate ON the mesh now (the VM runs inside the
            # shard_map body against replicated instance tables); only
            # genuinely unsupported shapes — caveated graphs without
            # per-edge caveat rows, i.e. hand-built unstratified
            # layouts — still route to the single-device path, counted
            # so a mesh deployment sees why its mesh idles.
            metrics.counter("engine_caveat_mesh_fallback_total").inc()
            return cg
        with self._lock:
            sg = self._sharded
            if sg is None or sg.cg is not cg:
                t0 = time.perf_counter()
                if sg is None:
                    sg = ShardedGraph(cg, self.mesh)
                    metrics.counter("engine_sharded_builds_total").inc()
                else:
                    # incremental revision: reuses the jitted shard_map +
                    # resident base shards, applies only the delta
                    sg = sg.updated(cg)
                    metrics.counter("engine_sharded_updates_total").inc()
                metrics.histogram("engine_sharded_build_seconds").observe(
                    time.perf_counter() - t0)
                self._sharded = sg
            return sg

    # bulk checks dispatch in chunks this size so host encode of the next
    # chunk overlaps device execution of the previous one
    CHECK_PIPELINE_CHUNK = 16384

    def _encode_checks(self, cg, objs, items):
        """Single-pass check-batch encode with per-(type, permission) and
        per-type caches inlined, instead of two encode_* calls per item —
        the two calls' attribute/dict traffic was over half the bulk-check
        wall time at 65k items on a TPU chip (106ms of 176ms). Semantics
        identical to ``encode_target`` / ``encode_subject``; the columnar
        numpy alternative measured SLOWER (string-array materialization
        dominates), so this stays a lean Python loop."""
        from ..ops.reachability import VOID_IDX

        n = len(items)
        M = cg.M
        offset_of = cg.offset_of
        type_sizes = cg.type_sizes
        q_slots = np.empty(n, dtype=np.int32)
        q_batch = np.empty(n, dtype=np.int32)
        tp_off: dict[tuple, int] = {}  # (type, permission) -> offset | -1
        ti: dict[str, tuple] = {}  # type -> (id map | None, type size)
        subjects: dict[tuple, int] = {}
        seed_rows: list[tuple[int, int]] = []
        for i, it in enumerate(items):
            t = it.resource_type
            key = (t, it.permission)
            off = tp_off.get(key)
            if off is None:
                o = offset_of(t, it.permission)
                off = -1 if o is None else o
                tp_off[key] = off
            if off < 0:
                q_slots[i] = M
            else:
                ent = ti.get(t)
                if ent is None:
                    interner = objs.get(t)
                    ent = (interner.id_map() if interner is not None
                           else None, type_sizes.get(t, 0))
                    ti[t] = ent
                to_id, size = ent
                if to_id is None:
                    q_slots[i] = off + VOID_IDX
                else:
                    oi = to_id.get(it.resource_id)
                    q_slots[i] = off + (
                        oi if oi is not None and oi < size else VOID_IDX)
            skey = (it.subject_type, it.subject_id, it.subject_relation)
            row = subjects.get(skey)
            if row is None:
                row = len(seed_rows)
                subjects[skey] = row
                seed_rows.append(
                    cg.encode_subject(it.subject_type, it.subject_id,
                                      it.subject_relation, objs)
                )
            q_batch[i] = row
        return np.asarray(seed_rows, dtype=np.int32), q_slots, q_batch

    def check_bulk_async(self, items: list[CheckItem],
                         now: Optional[float] = None,
                         context: Optional[dict] = None
                         ) -> "EngineFuture":
        """Dispatch a bulk check without blocking (device→host readback
        overlaps with other in-flight queries); ``.result()`` to wait.

        With the decision cache enabled (and no explicit ``now`` — a
        pinned clock must see the store exactly as of that instant, so it
        bypasses the cache), per-item verdicts are served from the cache
        and only the miss residue dispatches; the answer list reassembles
        in the caller's order. Verdicts — positive and negative — are
        cached keyed by the snapshot revision (plus the request-context
        digest when a caveat context rides the call) with the store's
        next-expiry watermark ∧ the caveat table's next verdict flip as
        deadline."""
        cache = self._decision_cache
        if cache is None or now is not None or not items:
            return self._check_bulk_dispatch(items, now, context=context)
        # pin ONE compiled snapshot for the whole bulk — hits are keyed
        # at its revision and the miss residue dispatches against the
        # same graph, so the answer list reflects a single revision even
        # when a write lands mid-call (the uncached path's one-snapshot
        # guarantee)
        cg = self.compiled()
        now0 = time.time()
        # the digest partitions cache keys ONLY when the graph actually
        # carries caveat instances, and ONLY over the context keys the
        # compiled caveats declare — an uncaveated graph's verdicts
        # cannot depend on request context at all, and digesting
        # undeclared fields (the middleware's per-request name/verb/...)
        # would fragment the repeat-traffic working set for nothing
        digest = (context_digest(cg.caveats.relevant_context(context))
                  if cg.caveats is not None and cg.caveats.metas
                  else None)
        keys = [check_key(cg.revision, it, digest) for it in items]
        out: list = [None] * len(items)
        miss_idx: list[int] = []
        for i, k in enumerate(keys):
            v = cache.get(k, now0)
            if v is MISS:
                miss_idx.append(i)
            else:
                out[i] = v
        if not miss_idx:
            return EngineFuture(None, lambda _: list(out))
        inner = self._check_bulk_dispatch(
            [items[i] for i in miss_idx], now0, cg=cg, context=context)

        def fin(_):
            got = inner.result()
            deadline = self._cache_deadline(cg, now0, context)
            for j, i in enumerate(miss_idx):
                v = bool(got[j])
                cache.put(keys[i], v, deadline, 0, now0)
                out[i] = v
            return list(out)

        return EngineFuture(None, fin, iters=inner.iterations)

    def _check_bulk_dispatch(self, items: list[CheckItem],
                             now: Optional[float] = None,
                             cg: Optional[CompiledGraph] = None,
                             context: Optional[dict] = None
                             ) -> "EngineFuture":
        """The raw (cache-less) bulk check: one chunked device pass.
        ``cg`` pins an already-obtained snapshot (the cached path passes
        the graph its hits were keyed against)."""
        if not items:
            return EngineFuture(None, lambda _: [])
        if cg is None:
            cg = self.compiled()
        objs = self._objects_by_name()
        t0 = time.perf_counter()
        self._apply_crossover(cg)
        backend = self._backend(cg)
        n = len(items)
        chunk = self.CHECK_PIPELINE_CHUNK
        if now is None:
            # one clock for the whole bulk call: every chunk's expiration
            # mask must see the same instant (one CheckBulkPermissions =
            # one consistency snapshot, reference check.go:41-48)
            now = time.time()
        # request caveat context encodes ONCE for the whole logical call
        # (chunks share it; a per-chunk encode would also multi-count
        # the request-list-overflow counter by the chunk count)
        cav_req = None
        cavs = cg.caveats
        if cavs is not None and cavs.metas:
            cav_req, _ = cavs.encode_request(context, now)
        # chunked pipeline: dispatches are async, so encoding chunk k+1 on
        # the host overlaps chunk k's device execution and readback —
        # wall ≈ one_chunk_encode + transport + device, not encode + both
        futs = []
        for s in range(0, n, chunk):
            seeds, q_slots, q_batch = self._encode_checks(
                cg, objs, items[s:s + chunk])
            futs.append(backend.query_async(seeds, q_slots, q_batch,
                                            now=now, context=context,
                                            cav_req=cav_req))
        metrics.counter("engine_checks_total").inc(n)
        metrics.histogram(
            "engine_dispatch_batch_rows",
            buckets=(1, 2, 4, 8, 16, 64, 256, 1024, 4096, 16384, 65536),
        ).observe(n)
        # leaf span (finished by fin, possibly on another thread): the
        # device-side share of a check when a trace is active
        dev_span = tracer.begin("device", kind="check", rows=n)

        def iters():
            return max(f.iterations() for f in futs)

        def fin(_):
            out = [bool(x) for f in futs for x in f.result()]
            # engine_check_seconds covers the WHOLE bulk call including
            # host-side encode (what a caller experiences), not just
            # dispatch+device+readback as before the chunked pipeline
            metrics.histogram("engine_check_seconds").observe(
                time.perf_counter() - t0)
            it = iters()
            metrics.histogram("engine_fixpoint_iterations").observe(it)
            self._count_semiring_modes(futs)
            # caveat instances that resolved missing-context this call:
            # denied fail-closed, and LOUD — this counter replaces the
            # old silent load-time exclusion of conditional grants.
            # Semantics: DISTINCT instances lacking context per logical
            # call (every chunk shares one graph + one context, so the
            # per-chunk counts are identical — max, not sum), counted
            # whether or not the queried slots depended on them (the
            # mask evaluates once for the whole graph per dispatch).
            missing = max((getattr(f, "caveats_missing", lambda: 0)()
                           for f in futs), default=0)
            if missing:
                metrics.counter(
                    "engine_caveat_denied_missing_context_total").inc(
                    missing)
            if dev_span is not None:
                dev_span.set("fixpoint_iters", it)
                dev_span.finish()
            return out

        return EngineFuture(None, fin, iters=iters)

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None,
                         context: Optional[dict] = None) -> list[str]:
        """LookupResources: ids of ``resource_type`` on which the subject has
        ``permission`` (reference lookups.go:49-65 streams these; we return
        the whole set from one device pass)."""
        mask, interner = self.lookup_resources_mask(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context)
        return mask_to_ids(mask, interner)

    def lookup_subjects(self, resource_type: str, resource_id: str,
                        permission: str, subject_type: str,
                        subject_relation: Optional[str] = None,
                        now: Optional[float] = None,
                        context: Optional[dict] = None,
                        chunk: int = 4096) -> list[str]:
        """LookupSubjects: which subjects of ``subject_type`` hold
        ``permission`` on one resource — the reverse of
        :meth:`lookup_resources` (reference LookupSubjects RPC; the
        reconcile/debug shape "who can see this namespace?").

        Evaluated as bulk checks over the store's KNOWN subject universe
        (every distinct ``subject_type`` subject id appearing in any
        relationship): the forward fixpoint batches subjects along B
        already, so a reverse walk buys nothing a chunked bulk check
        doesn't, and checks honor wildcard grants — a ``user:*`` tuple
        makes every known subject pass. Wildcards are reported as the
        checks resolve them (concrete ids), never as a literal ``'*'``
        row. Sorted for determinism."""
        from .store import RelationshipFilter

        cands = sorted({
            rel.subject_id
            for rel in self.read_relationships(
                RelationshipFilter(subject_type=subject_type))
            if rel.subject_id != "*"
        })
        out: list[str] = []
        for i in range(0, len(cands), chunk):
            part = cands[i:i + chunk]
            got = self.check_bulk(
                [CheckItem(resource_type, resource_id, permission,
                           subject_type, sid, subject_relation)
                 for sid in part], now=now, context=context)
            out.extend(sid for sid, ok in zip(part, got) if ok)
        metrics.counter("engine_lookup_subjects_total").inc()
        return out

    def lookup_resources_mask(self, resource_type: str, permission: str,
                              subject_type: str, subject_id: str,
                              subject_relation: Optional[str] = None,
                              now: Optional[float] = None,
                              context: Optional[dict] = None):
        """Vectorized variant for the list-filter hot path: returns
        (bool mask over the type's object index space, per-type interner).
        Callers with a list of candidate names map name->index and test the
        mask directly — no per-object RPC or string materialization."""
        return self.lookup_resources_mask_async(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context,
        ).result()

    def lookup_resources_mask_async(self, resource_type: str, permission: str,
                                    subject_type: str, subject_id: str,
                                    subject_relation: Optional[str] = None,
                                    now: Optional[float] = None,
                                    context: Optional[dict] = None):
        """Non-blocking mask lookup; ``.result()`` -> (mask, interner).
        Concurrent list requests dispatch back-to-back and overlap their
        readbacks — the reference's goroutine-per-prefilter overlap
        (pkg/authz/responsefilterer.go:165-183) without the goroutines.
        With batching enabled, concurrent calls fuse into one dispatch.

        The decision cache (when enabled, now-less queries only) sits in
        front of everything: repeats at an unchanged revision are served
        host-side with zero device work, and concurrent identical misses
        singleflight — one caller dispatches (through the batcher when
        enabled, which therefore only ever sees true misses), the rest
        piggyback on its future. Cached masks are copied on read so no
        caller can mutate the cache's array."""
        cache = self._decision_cache
        if cache is None or now is not None:
            return self._lookup_submit(resource_type, permission,
                                       subject_type, subject_id,
                                       subject_relation, now, context)
        cg = self.compiled()
        key = lookup_key(cg.revision, resource_type, permission,
                         subject_type, subject_id, subject_relation,
                         context_digest(cg.caveats.relevant_context(
                             context))
                         if cg.caveats is not None and cg.caveats.metas
                         else None)
        now0 = time.time()
        hit = cache.get(key, now0)
        if hit is not MISS:
            mask, interner = hit
            return EngineFuture(None, lambda _: (
                None if mask is None else mask.copy(), interner))
        leader, flight = cache.flight(key, now0)
        if not leader:

            def fin_follower(_):
                mask, interner = flight.result()
                return (None if mask is None else mask.copy(), interner)

            return EngineFuture(None, fin_follower)
        try:
            inner = self._lookup_submit(resource_type, permission,
                                        subject_type, subject_id,
                                        subject_relation, None, context)
        except BaseException as e:  # dispatch died before a future existed
            flight.abort(e)
            cache.release(key, flight)
            raise

        def finish():
            try:
                value = inner.result()
            except BaseException:
                cache.release(key, flight)  # errors are never cached
                raise
            mask, interner = value
            deadline = self._cache_deadline(cg, now0, context)
            flight.deadline = deadline
            cache.put(key, (mask, interner), deadline,
                      0 if mask is None else int(mask.nbytes), now0)
            cache.release(key, flight)
            return value

        flight.launch(finish)

        def fin_leader(_):
            mask, interner = flight.result()
            return (None if mask is None else mask.copy(), interner)

        return EngineFuture(None, fin_leader,
                            iters=getattr(inner, "iterations", None))

    def _lookup_submit(self, resource_type: str, permission: str,
                       subject_type: str, subject_id: str,
                       subject_relation: Optional[str],
                       now: Optional[float],
                       context: Optional[dict] = None):
        """Route one true-miss lookup: fused through the batcher when
        enabled, direct otherwise."""
        cg = self._compiled
        # a request context only matters when the graph actually holds
        # caveat instances: a fused batch evaluates ONE caveat mask per
        # dispatch, so rows with different contexts cannot share it —
        # but contexted lookups against a provably caveat-less current
        # graph still fuse (the middleware sends context on EVERY
        # request; bypassing unconditionally would disable batching)
        ctx_matters = bool(context) and not (
            cg is not None and cg.revision == self.store.revision
            and (cg.caveats is None or not cg.caveats.metas))
        if self._batcher is not None and now is None and not ctx_matters:
            # explicit-now callers bypass the batcher: a fused batch runs
            # at one dispatch-time clock, which is only equivalent to the
            # unbatched path for now-less queries
            return self._batcher.submit(
                resource_type, permission, subject_type, subject_id,
                subject_relation)
        return self._lookup_direct(resource_type, permission, subject_type,
                                   subject_id, subject_relation, now,
                                   context)

    # -- semiring mode feedback ---------------------------------------------

    def _apply_crossover(self, cg: CompiledGraph) -> None:
        """Stamp the occupancy-derived push/pull crossover onto the
        snapshot about to dispatch. It rides the dispatch as a TRACED
        scalar (ops/semiring.propagate branches on it with lax.cond), so
        retuning per request costs zero recompiles. A freshly compiled
        graph starts back at 1.0 (always-push) only until the engine's
        EWMA re-stamps it here."""
        cg.spmm_crossover = semiring.crossover_from_occupancy(
            self._occ_ewma)
        # the crossover was invisible to operators before this gauge:
        # auto mode's push/pull choice is made ON DEVICE per iteration,
        # and the only host-side artifacts are this threshold and the
        # per-mode step counters below
        metrics.gauge("engine_semiring_crossover").set(cg.spmm_crossover)

    def _observe_occupancy(self, frac: float) -> None:
        """Fold one observed final-frontier fill fraction ([0, 1], from
        the ``engine_frontier_occupancy`` readback accounting) into the
        EWMA that drives :meth:`_apply_crossover`."""
        e = self._occ_ewma
        self._occ_ewma = frac if e is None else 0.9 * e + 0.1 * frac

    @staticmethod
    def _count_semiring_modes(futs) -> None:
        """Per-mode hop counters off completed futures: how many semiring
        hops took the push (bit-packed) vs pull (dense matmul) branch.
        ``push_steps`` may exceed ``iterations()`` (acyclic level
        applications count toward pushes but not core iterations), so the
        pull share clamps at zero."""
        push = pull = 0
        for f in futs:
            p = getattr(f, "push_steps", lambda: 0)()
            push += p
            pull += max(f.iterations() - p, 0)
        if push:
            metrics.counter("engine_semiring_push_steps_total").inc(push)
        if pull:
            metrics.counter("engine_semiring_pull_steps_total").inc(pull)

    def _lookup_direct(self, resource_type: str, permission: str,
                       subject_type: str, subject_id: str,
                       subject_relation: Optional[str],
                       now: Optional[float],
                       context: Optional[dict] = None):
        cg = self.compiled()
        objs = self._objects_by_name()
        off = cg.offset_of(resource_type, permission)
        n = cg.type_sizes.get(resource_type)
        interner = objs.get(resource_type)
        if off is None or interner is None:
            # trivial lookups (unknown type/permission) count too — the
            # batched path already counts them in LookupBatcher._dispatch,
            # and tests read engine_lookups_total as "lookups the engine
            # answered", cache hits excluded
            metrics.counter("engine_lookups_total").inc()
            return EngineFuture(None, lambda _: (None, None))
        seeds = np.asarray(
            [cg.encode_subject(subject_type, subject_id, subject_relation, objs)],
            dtype=np.int32,
        )
        qk = (off, n)
        ent = self._q_host.get(qk)
        if ent is None:
            if len(self._q_host) >= 64:
                try:
                    # pop-with-default: concurrent lookups may race the
                    # same oldest key (no lock on this path by design);
                    # RuntimeError = the dict mutated between iter() and
                    # next() — skip this eviction, the cache is bounded
                    # by whoever wins
                    self._q_host.pop(next(iter(self._q_host)), None)
                except (StopIteration, RuntimeError):
                    pass
            ent = (off + np.arange(n, dtype=np.int32),
                   np.zeros(n, dtype=np.int32))
            self._q_host[qk] = ent
        q_slots, q_batch = ent
        t0 = time.perf_counter()
        # the query arrays are a pure function of (type, permission) slot
        # layout: cache their device copies across queries (the ~0.5MB
        # upload per 100k-object lookup otherwise dominates wall latency
        # on remotely-attached chips)
        self._apply_crossover(cg)
        fut = self._backend(cg).query_async(
            seeds, q_slots, q_batch, now=now,
            q_cache_key=("lookup", off, n), q_contiguous=True,
            context=context)
        metrics.counter("engine_lookups_total").inc()
        metrics.histogram(
            "engine_dispatch_batch_rows",
            buckets=(1, 2, 4, 8, 16, 64, 256, 1024, 4096, 16384, 65536),
        ).observe(n)
        dev_span = tracer.begin("device", kind="lookup", rows=n)

        def fin(out):
            metrics.histogram("engine_lookup_seconds").observe(
                time.perf_counter() - t0)
            it = fut.iterations()
            metrics.histogram("engine_fixpoint_iterations").observe(it)
            missing = getattr(fut, "caveats_missing", lambda: 0)()
            if missing:
                metrics.counter(
                    "engine_caveat_denied_missing_context_total").inc(
                    missing)
            # QueryFuture.result() already materialized a fresh host
            # array; only copy again if it came back read-only
            m = np.asarray(out)
            if not m.flags.writeable:
                m = m.copy()
            m = mask_pseudo_objects(m)
            # final-frontier occupancy: how much of the queried slot
            # window the reachable set filled (TrieJax-style frontier
            # accounting, host-side off the readback — no device cost)
            occ = int(m.sum())
            metrics.histogram(
                "engine_frontier_occupancy",
                buckets=(0, 1, 8, 64, 512, 4096, 32768, 262144, 2**21),
            ).observe(occ)
            # ... and close the loop: the observed fill fraction feeds
            # the EWMA behind the semiring push/pull crossover, so dense
            # workloads drift the dense phase onto the MXU pull path
            self._observe_occupancy(float(occ) / max(m.size, 1))
            self._count_semiring_modes((fut,))
            if dev_span is not None:
                dev_span.set("fixpoint_iters", it)
                dev_span.set("frontier_occupancy", occ)
                dev_span.finish()
            return m, interner

        return EngineFuture(fut, fin)

    # -- durability ---------------------------------------------------------

    def save_snapshot(self, path: str) -> None:
        """Persist the relationship store (compacted, atomic) — the graph
        analog of the reference's durable state; a restored engine skips
        the bulk re-load entirely (51s at the 10M-relationship scale)."""
        self.store.save(path)

    def load_snapshot(self, path: str) -> None:
        with self._lock:
            if self._persistence is not None:
                # a file restore bypasses the journal: the WAL would
                # replay over the wrong lineage on the next boot
                raise StoreError(
                    "load_snapshot is incompatible with an enabled "
                    "persistence data dir (recovery owns restores)")
            self.store.load(path)
            self._compiled = None

    def load_snapshot_if_exists(self, path: Optional[str]) -> bool:
        """Boot-time restore shared by every entry point (proxy options,
        engine host CLI): load when the file exists, report whether it
        did."""
        import os

        if not path or not os.path.exists(path):
            return False
        self.load_snapshot(path)
        return True

    # -- watch --------------------------------------------------------------

    @property
    def revision(self) -> int:
        return self.store.revision

    def watch_since(self, revision: int) -> list[WatchEvent]:
        sup = self._watch_suppress
        return [
            WatchEvent(r.revision, "touch" if r.op == 2 else "delete", r.rel)
            for r in self.store.watch_since(revision)
            if r.revision not in sup
        ]

    def wait_events(self, revision: int, timeout: float) -> list[WatchEvent]:
        """Block until events past ``revision`` land (or ``timeout`` — then
        ``[]``). The push-latency form of :meth:`watch_since`: the watch
        hub parks ONE thread here per engine instead of every watcher
        polling on an interval. Migration-backfill echo revisions are
        filtered here too (an empty list after a suppressed-only batch
        just looks like a timeout to the hub, which re-parks)."""
        sup = self._watch_suppress
        return [
            WatchEvent(r.revision, "touch" if r.op == 2 else "delete", r.rel)
            for r in self.store.wait_since(revision, timeout)
            if r.revision not in sup
        ]

    # -- live schema migration (migration/migrator.py) -----------------------

    def begin_schema_migration(self, schema_text: str,
                               record_path: Optional[str] = None,
                               wait: bool = False, **cfg) -> dict:
        """Start a zero-downtime migration of this engine to the schema
        in ``schema_text``: diff-classify, dual-compile, journaled
        backfill, and an atomic revision-preserving cutover. Returns the
        initial status dict; ``wait=True`` blocks until done/failed.
        Raises :class:`~...models.schema.IncompatibleSchemaChange` (a
        ``SchemaError``) before any state changes when the transition is
        not performable online."""
        from ..migration import SchemaMigrator

        with self._lock:
            if self._migrator is not None and self._migrator.active:
                raise StoreError("a schema migration is already running")
            prev = self._migrator
            m = SchemaMigrator(self, schema_text,
                               record_path=record_path
                               or self._default_migration_record(), **cfg)
            self._migrator = m
        try:
            m.start()
        except BaseException:
            # a refused plan (e.g. incompatible diff) must not leave a
            # never-started migrator installed as "active" — that would
            # refuse every future begin
            with self._lock:
                if self._migrator is m:
                    self._migrator = prev
            raise
        if wait:
            m.join()
        return m.status()

    def _default_migration_record(self) -> Optional[str]:
        """Persist the migration phase machine beside the WAL when the
        engine is durable; memory-only engines migrate without a record
        (a crash loses the store anyway, so there is nothing to replay
        the phases against)."""
        p = self._persistence
        d = getattr(p, "data_dir", None) if p is not None else None
        if d is None:
            return None
        import os

        return os.path.join(d, "migration.json")

    def migration_status(self) -> Optional[dict]:
        """Phase/lag status of the running (or last) migration, or
        ``None`` when this engine never migrated — the /readyz and
        remote-op probe surface."""
        m = self._migrator
        return None if m is None else m.status()

    def abort_schema_migration(self) -> dict:
        """Abort the running migration (refused once any cut happened —
        the same one-way rule as the rebalancer's transition)."""
        m = self._migrator
        if m is None:
            raise StoreError("no schema migration to abort")
        return m.abort()

    def cut_schema_migration(self, wait: bool = True) -> dict:
        """Release a migration holding at the dual phase into its
        cutover (the planner's coordinated-cut hook). Idempotent: a
        migration already cut (or done) returns its status."""
        m = self._migrator
        if m is None:
            raise StoreError("no schema migration to cut")
        m.request_cut()
        if wait:
            m.join()
        return m.status()

    def recover_schema_migration(self,
                                 record_path: Optional[str] = None
                                 ) -> Optional[dict]:
        """Boot-time crash matrix: consult the persisted migration
        record (if any) and either cleanly abort (no cut persisted) or
        resume/finish the cutover (cut persisted). Returns the recovery
        outcome dict or ``None`` when there was nothing to recover."""
        from ..migration import recover

        return recover(self, record_path
                       or self._default_migration_record())

    # -- debugging ----------------------------------------------------------

    def oracle(self, now: Optional[float] = None,
               context: Optional[dict] = None) -> OracleEvaluator:
        return OracleEvaluator(self.schema, self.store.snapshot(),
                               now=now, context=context)
