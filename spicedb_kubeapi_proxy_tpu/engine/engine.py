"""The query engine: SpiceDB-equivalent API over the TPU reachability path.

Public surface mirrors what the reference proxy consumes from authzed-go
(SURVEY.md §2.5): WriteRelationships (create/touch/delete + preconditions),
ReadRelationships, DeleteRelationships(filter), CheckPermission /
CheckBulkPermissions, LookupResources, and Watch. All queries are fully
consistent — the reference always requests full consistency
(/root/reference/pkg/authz/check.go:42-44, lookups.go:50-52) — implemented
as compile-on-demand: a query against a stale snapshot recompiles first.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from ..models.bootstrap import Bootstrap, DEFAULT_BOOTSTRAP, parse_bootstrap
from ..models.schema import Schema
from ..models.tuples import Relationship
from ..ops.reachability import (
    CompiledGraph,
    MAX_DELTA_RECORDS,
    compile_graph,
    incremental_update,
)
from ..utils.metrics import metrics
from .evaluator import OracleEvaluator
from .store import (
    Precondition,
    RelationshipFilter,
    Store,
    StoreError,
    WatchRecord,
    WriteOp,
)


class SchemaViolation(StoreError):
    pass


@dataclass(frozen=True)
class CheckItem:
    resource_type: str
    resource_id: str
    permission: str
    subject_type: str
    subject_id: str
    subject_relation: Optional[str] = None


@dataclass(frozen=True)
class WatchEvent:
    revision: int
    operation: str  # "touch" | "delete"
    relationship: Relationship


def mask_pseudo_objects(mask: np.ndarray) -> np.ndarray:
    """Clear the reserved per-type pseudo-object indices (0 = void,
    1 = the wildcard object '*') from a lookup mask — shared by the direct
    and batched lookup paths so the slot layout lives in one place."""
    mask[0] = False
    mask[1] = False
    return mask


class EngineFuture:
    """A dispatched engine query: ``result()`` blocks and post-processes.
    ``fut`` is a :class:`~...ops.reachability.QueryFuture` or ``None`` for
    trivially-resolved queries."""

    __slots__ = ("_fut", "_fin")

    def __init__(self, fut, fin):
        self._fut = fut
        self._fin = fin

    def result(self):
        return self._fin(None if self._fut is None else self._fut.result())


class Engine:
    """In-process relationship-graph engine (the ``embedded://`` / ``tpu://``
    backend). Thread-safe."""

    def __init__(self, bootstrap: Optional[str] = None,
                 schema: Optional[Schema] = None,
                 validate_writes: bool = True,
                 mesh=None):
        if schema is None:
            b: Bootstrap = parse_bootstrap(bootstrap or DEFAULT_BOOTSTRAP)
            schema = b.schema
            seed = b.relationships
        else:
            seed = []
        self.schema = schema
        self.store = Store()
        self.validate_writes = validate_writes
        self._lock = threading.RLock()
        self._compiled: Optional[CompiledGraph] = None
        self._batcher = None
        # optional jax.sharding.Mesh ("data", "graph" axes): queries route
        # through a ShardedGraph pinned across it instead of one device
        self.mesh = mesh
        self._sharded = None
        if seed:
            self.write_relationships([WriteOp("touch", r) for r in seed])

    def enable_lookup_batching(self, window: float = 0.002,
                               max_rows: int = 8) -> None:
        """Coalesce concurrent lookup_resources_mask calls into fused
        device dispatches (engine/batcher.py) — trades up to ``window``
        seconds of added latency for one dispatch per ``max_rows``
        concurrent list prefilters."""
        from .batcher import LookupBatcher

        self._batcher = LookupBatcher(self, window=window, max_rows=max_rows)

    # -- write path ---------------------------------------------------------

    def _validate(self, rel: Relationship) -> None:
        d = self.schema.definitions.get(rel.resource_type)
        if d is None:
            raise SchemaViolation(f"unknown resource type {rel.resource_type!r}")
        if rel.resource_id == "*":
            # SpiceDB forbids wildcard resource ids; only subjects may be '*'
            raise SchemaViolation("resource id may not be the wildcard '*'")
        r = d.relations.get(rel.relation)
        if r is None:
            raise SchemaViolation(
                f"{rel.resource_type} has no relation {rel.relation!r}"
                + (" (permissions are not writable)"
                   if rel.relation in d.permissions else "")
            )
        sub_def = self.schema.definitions.get(rel.subject_type)
        if sub_def is None:
            raise SchemaViolation(f"unknown subject type {rel.subject_type!r}")
        ok = False
        expiration_blocked = False
        for a in r.allowed:
            if a.type != rel.subject_type:
                continue
            if rel.subject_id == "*":
                if not a.wildcard:
                    continue
            elif a.wildcard or (a.relation or None) != rel.subject_relation:
                continue
            if rel.expiration is not None and not a.expiration:
                # another allowed entry of the same subject type may carry
                # the expiration trait (e.g. `user | user with expiration`)
                # — keep scanning instead of rejecting on the first match
                expiration_blocked = True
                continue
            ok = True
            break
        if not ok and expiration_blocked:
            raise SchemaViolation(
                f"{rel.resource_type}#{rel.relation} does not allow "
                "expiring relationships"
            )
        if not ok:
            raise SchemaViolation(
                f"subject {rel.subject_type}"
                + (f"#{rel.subject_relation}" if rel.subject_relation else "")
                + f" not allowed on {rel.resource_type}#{rel.relation}"
            )
        if rel.subject_relation:
            if not self.schema.definitions[rel.subject_type].relation_or_permission(
                rel.subject_relation
            ):
                raise SchemaViolation(
                    f"{rel.subject_type} has no relation "
                    f"{rel.subject_relation!r}"
                )

    def write_relationships(self, ops: list[WriteOp],
                            preconditions: list[Precondition] = ()) -> int:
        if self.validate_writes:
            for op in ops:
                self._validate(op.rel)
        return self.store.write(list(ops), list(preconditions))

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list[Precondition] = ()) -> int:
        return self.store.delete_by_filter(f, list(preconditions))

    def read_relationships(self, f: RelationshipFilter) -> Iterator[Relationship]:
        return self.store.read(f)

    def bulk_load(self, rels_cols: dict) -> int:
        return self.store.bulk_load(rels_cols)

    # -- query path ---------------------------------------------------------

    def _objects_by_name(self) -> dict:
        # snapshot under the store lock: writers intern new types into
        # store.objects and a concurrent iteration would race
        with self.store._lock:
            return {
                self.store.types.string(tid): it
                for tid, it in self.store.objects.items()
            }

    def compiled(self) -> CompiledGraph:
        """Fully-consistent snapshot: a stale compiled graph is brought
        current by an O(delta) incremental update (small writes — the
        dual-write hot path) or a full recompile (bulk loads, schema-shaped
        changes, oversized deltas)."""
        with self._lock:
            cur = self._compiled
            if cur is not None and cur.revision != self.store.revision:
                inc = self._try_incremental(cur)
                if inc is not None:
                    self._compiled = inc
                    return inc
            if self._compiled is None or \
               self._compiled.revision != self.store.revision:
                t0 = time.perf_counter()
                self._compiled = compile_graph(self.schema, self.store.snapshot())
                metrics.counter("engine_graph_compiles_total").inc()
                metrics.histogram("engine_graph_compile_seconds").observe(
                    time.perf_counter() - t0)
            return self._compiled

    def _try_incremental(self, cur: CompiledGraph) -> Optional[CompiledGraph]:
        from ..utils.features import features

        if not features.enabled("IncrementalGraphUpdates"):
            return None
        st = self.store
        with st._lock:
            if cur.revision < st.unlogged_revision:
                return None  # bulk-loaded/restored changes aren't in the log
            try:
                records = st.watch_since(cur.revision)
            except StoreError:
                return None  # history trimmed past our revision
            rev = st.revision
        if len(records) > MAX_DELTA_RECORDS:
            return None
        t0 = time.perf_counter()
        from .store import OP_DELETE

        delta = [(r.op == OP_DELETE, r.rel) for r in records]
        new = incremental_update(cur, delta, rev, st)
        if new is not None:
            metrics.counter("engine_graph_incremental_updates_total").inc()
            metrics.histogram("engine_graph_incremental_seconds").observe(
                time.perf_counter() - t0)
        return new

    def check(self, item: CheckItem, now: Optional[float] = None) -> bool:
        return self.check_bulk([item], now=now)[0]

    def check_bulk(self, items: list[CheckItem],
                   now: Optional[float] = None) -> list[bool]:
        """CheckBulkPermissions: evaluate all items in one device pass,
        batching distinct subjects along B (reference check.go:22-48 issues
        one bulk RPC per request; here the whole bulk is one fixpoint)."""
        return self.check_bulk_async(items, now=now).result()

    def _backend(self, cg: CompiledGraph):
        """The query executor for a compiled graph: the graph itself
        (single device) or a mesh-pinned ShardedGraph, rebuilt whenever the
        compiled graph changes revision. Both expose the same
        ``query_async(seeds, q_slots, q_batch, now)`` surface."""
        if self.mesh is None:
            return cg
        with self._lock:
            sg = self._sharded
            if sg is None or sg.cg is not cg:
                from ..parallel.sharded import ShardedGraph

                t0 = time.perf_counter()
                if sg is None:
                    sg = ShardedGraph(cg, self.mesh)
                    metrics.counter("engine_sharded_builds_total").inc()
                else:
                    # incremental revision: reuses the jitted shard_map +
                    # resident base shards, applies only the delta
                    sg = sg.updated(cg)
                    metrics.counter("engine_sharded_updates_total").inc()
                metrics.histogram("engine_sharded_build_seconds").observe(
                    time.perf_counter() - t0)
                self._sharded = sg
            return sg

    def check_bulk_async(self, items: list[CheckItem],
                         now: Optional[float] = None) -> "EngineFuture":
        """Dispatch a bulk check without blocking (device→host readback
        overlaps with other in-flight queries); ``.result()`` to wait."""
        if not items:
            return EngineFuture(None, lambda _: [])
        cg = self.compiled()
        objs = self._objects_by_name()
        subjects: dict[tuple, int] = {}
        seed_rows: list[tuple[int, int]] = []
        q_slots = np.empty(len(items), dtype=np.int32)
        q_batch = np.empty(len(items), dtype=np.int32)
        for i, it in enumerate(items):
            skey = (it.subject_type, it.subject_id, it.subject_relation)
            row = subjects.get(skey)
            if row is None:
                row = len(seed_rows)
                subjects[skey] = row
                seed_rows.append(
                    cg.encode_subject(it.subject_type, it.subject_id,
                                      it.subject_relation, objs)
                )
            q_slots[i] = cg.encode_target(it.resource_type, it.permission,
                                          it.resource_id, objs)
            q_batch[i] = row
        seeds = np.asarray(seed_rows, dtype=np.int32)
        t0 = time.perf_counter()
        fut = self._backend(cg).query_async(seeds, q_slots, q_batch, now=now)
        metrics.counter("engine_checks_total").inc(len(items))

        def fin(out):
            metrics.histogram("engine_check_seconds").observe(
                time.perf_counter() - t0)
            metrics.histogram("engine_fixpoint_iterations").observe(
                fut.iterations())
            return [bool(x) for x in out]

        return EngineFuture(fut, fin)

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None) -> list[str]:
        """LookupResources: ids of ``resource_type`` on which the subject has
        ``permission`` (reference lookups.go:49-65 streams these; we return
        the whole set from one device pass)."""
        mask, interner = self.lookup_resources_mask(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now)
        if mask is None:
            return []
        # the mask covers the bucket-padded object space; padding indices
        # can never be true (no edges) but guard the interner bound anyway
        return [interner.string(i) for i in np.flatnonzero(mask).tolist()
                if i < len(interner)]

    def lookup_resources_mask(self, resource_type: str, permission: str,
                              subject_type: str, subject_id: str,
                              subject_relation: Optional[str] = None,
                              now: Optional[float] = None):
        """Vectorized variant for the list-filter hot path: returns
        (bool mask over the type's object index space, per-type interner).
        Callers with a list of candidate names map name->index and test the
        mask directly — no per-object RPC or string materialization."""
        return self.lookup_resources_mask_async(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now,
        ).result()

    def lookup_resources_mask_async(self, resource_type: str, permission: str,
                                    subject_type: str, subject_id: str,
                                    subject_relation: Optional[str] = None,
                                    now: Optional[float] = None):
        """Non-blocking mask lookup; ``.result()`` -> (mask, interner).
        Concurrent list requests dispatch back-to-back and overlap their
        readbacks — the reference's goroutine-per-prefilter overlap
        (pkg/authz/responsefilterer.go:165-183) without the goroutines.
        With batching enabled, concurrent calls fuse into one dispatch."""
        if self._batcher is not None and now is None:
            # explicit-now callers bypass the batcher: a fused batch runs
            # at one dispatch-time clock, which is only equivalent to the
            # unbatched path for now-less queries
            return self._batcher.submit(
                resource_type, permission, subject_type, subject_id,
                subject_relation)
        cg = self.compiled()
        objs = self._objects_by_name()
        off = cg.offset_of(resource_type, permission)
        n = cg.type_sizes.get(resource_type)
        interner = objs.get(resource_type)
        if off is None or interner is None:
            return EngineFuture(None, lambda _: (None, None))
        seeds = np.asarray(
            [cg.encode_subject(subject_type, subject_id, subject_relation, objs)],
            dtype=np.int32,
        )
        q_slots = off + np.arange(n, dtype=np.int32)
        q_batch = np.zeros(n, dtype=np.int32)
        t0 = time.perf_counter()
        # the query arrays are a pure function of (type, permission) slot
        # layout: cache their device copies across queries (the ~0.5MB
        # upload per 100k-object lookup otherwise dominates wall latency
        # on remotely-attached chips)
        fut = self._backend(cg).query_async(
            seeds, q_slots, q_batch, now=now,
            q_cache_key=("lookup", off, n))
        metrics.counter("engine_lookups_total").inc()

        def fin(out):
            metrics.histogram("engine_lookup_seconds").observe(
                time.perf_counter() - t0)
            metrics.histogram("engine_fixpoint_iterations").observe(
                fut.iterations())
            return mask_pseudo_objects(np.array(out)), interner

        return EngineFuture(fut, fin)

    # -- durability ---------------------------------------------------------

    def save_snapshot(self, path: str) -> None:
        """Persist the relationship store (compacted, atomic) — the graph
        analog of the reference's durable state; a restored engine skips
        the bulk re-load entirely (51s at the 10M-relationship scale)."""
        self.store.save(path)

    def load_snapshot(self, path: str) -> None:
        with self._lock:
            self.store.load(path)
            self._compiled = None

    def load_snapshot_if_exists(self, path: Optional[str]) -> bool:
        """Boot-time restore shared by every entry point (proxy options,
        engine host CLI): load when the file exists, report whether it
        did."""
        import os

        if not path or not os.path.exists(path):
            return False
        self.load_snapshot(path)
        return True

    # -- watch --------------------------------------------------------------

    @property
    def revision(self) -> int:
        return self.store.revision

    def watch_since(self, revision: int) -> list[WatchEvent]:
        return [
            WatchEvent(r.revision, "touch" if r.op == 2 else "delete", r.rel)
            for r in self.store.watch_since(revision)
        ]

    # -- debugging ----------------------------------------------------------

    def oracle(self, now: Optional[float] = None) -> OracleEvaluator:
        return OracleEvaluator(self.schema, self.store.snapshot(), now=now)
