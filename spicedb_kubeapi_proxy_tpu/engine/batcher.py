"""Cross-request lookup batching: coalesce concurrent LookupResources
queries into one device dispatch.

The reference overlaps concurrent prefilters with goroutines, but each
still costs SpiceDB a full LookupResources dispatch
(/root/reference/pkg/authz/responsefilterer.go:165-183). On TPU the batch
axis is nearly free below the bit-kernel ceiling (ops/bitprop.py
BIT_B_MAX): this batcher holds a lookup for at most ``window`` seconds,
fusing up to ``max_rows`` concurrent subjects into ONE fixpoint whose
q_slots concatenate every caller's slot range (q_batch maps slots to
batch rows). 256 concurrent list requests (BASELINE config 5) become ~32
dispatches instead of 256.

Thread-safe and synchronous-friendly: callers run in worker threads
(asyncio.to_thread); futures block on an event. Errors propagate to every
caller of the affected flush.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class BatchedLookup:
    """One caller's pending lookup. ``result()`` blocks until the batch is
    DISPATCHED, then materializes from the shared device future — so the
    submitting threads never block on device execution (the non-blocking
    contract of lookup_resources_mask_async holds through the batcher)."""

    __slots__ = ("_event", "_thunk", "_value", "_error", "_done")

    def __init__(self):
        self._event = threading.Event()
        self._thunk = None
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = False

    def _resolve(self, thunk) -> None:
        self._thunk = thunk
        self._event.set()

    def _reject(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def result(self):
        self._event.wait()
        if not self._done:
            if self._error is None:
                try:
                    self._value = self._thunk()
                except BaseException as e:  # noqa: BLE001
                    self._error = e
            self._done = True
        if self._error is not None:
            raise self._error
        return self._value


class LookupBatcher:
    """Coalesces ``lookup_resources_mask`` calls across threads."""

    def __init__(self, engine, window: float = 0.002, max_rows: int = 8):
        self.engine = engine
        self.window = window
        self.max_rows = max_rows
        self._lock = threading.Lock()
        self._pending: list[tuple] = []  # (args tuple, BatchedLookup)
        self._timer: Optional[threading.Timer] = None
        self._closed = False

    def submit(self, resource_type: str, permission: str, subject_type: str,
               subject_id: str,
               subject_relation: Optional[str]) -> BatchedLookup:
        """Only now-less lookups batch (callers pinning an explicit
        evaluation time bypass the batcher — the engine dispatches those
        directly), so one dispatch-time clock is correct for the whole
        fused batch, exactly like the unbatched path.

        A late submit racing ``close()`` (disable_lookup_batching during
        shutdown reads ``engine._batcher`` before it is nulled) falls
        through to the direct engine path instead of queueing into a dead
        batcher whose timer will never fire."""
        fut = BatchedLookup()
        with self._lock:
            closed = self._closed
            batch = None
            if not closed:
                self._pending.append(
                    ((resource_type, permission, subject_type, subject_id,
                      subject_relation), fut))
                n = len(self._pending)
                if n >= self.max_rows:
                    batch = self._take_locked()
                elif n == 1:
                    self._timer = threading.Timer(self.window,
                                                  self._on_timer)
                    self._timer.daemon = True
                    self._timer.start()
        if closed:
            return self.engine._lookup_direct(
                resource_type, permission, subject_type, subject_id,
                subject_relation, None)
        if batch:
            self._flush(batch)
        return fut

    def _take_locked(self) -> list:
        batch = self._pending
        self._pending = []
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return batch

    def _on_timer(self) -> None:
        with self._lock:
            batch = self._take_locked()
        if batch:
            self._flush(batch)

    def _flush(self, batch: list) -> None:
        try:
            self._dispatch(batch)
        except BaseException as e:  # noqa: BLE001 - fan the error out
            for _, fut in batch:
                fut._reject(e)

    def _dispatch(self, batch: list) -> None:
        import time

        from ..utils.metrics import metrics
        from .engine import mask_pseudo_objects

        metrics.counter("engine_lookup_batches_total").inc()
        metrics.counter("engine_lookups_total").inc(len(batch))
        e = self.engine
        cg = e.compiled()
        objs = e._objects_by_name()
        # canonicalize row order by (off, n): row assignment is arbitrary
        # (futures map back positionally via metas), and sorting collapses
        # the composition cache key from permutations to combinations
        def row_key(item):
            (rt, perm, _st, _sid, _srl), _fut = item
            off = cg.offset_of(rt, perm)
            return (-1 if off is None else off,
                    cg.type_sizes.get(rt) or 0)

        batch = sorted(batch, key=row_key)
        seeds = []
        q_parts = []
        qb_parts = []
        composition = []  # (off, n) per row: the fused-grid cache key
        metas = []  # (fut, interner, n) | (fut, None, 0) for trivial misses
        for (rt, perm, st, sid, srl), fut in batch:
            off = cg.offset_of(rt, perm)
            n = cg.type_sizes.get(rt)
            interner = objs.get(rt)
            if off is None or interner is None:
                metas.append((fut, None, 0))
                continue
            row = len(seeds)
            seeds.append(cg.encode_subject(st, sid, srl, objs))
            q_parts.append(off + np.arange(n, dtype=np.int32))
            qb_parts.append(np.full(n, row, dtype=np.int32))
            composition.append((off, n))
            metas.append((fut, interner, n))
        t0 = time.perf_counter()
        if seeds:
            # the fused query arrays are a pure function of the (sorted)
            # row composition: cache their device copies — concurrent
            # lists of the same resource types repeat the composition, and
            # re-uploading B x objects of slot ids per dispatch is
            # measurable tunnel traffic. A single-row batch shares the
            # direct lookup path's key (identical array bytes).
            if len(composition) == 1:
                key = ("lookup",) + composition[0]
            else:
                key = ("lookup_batch", tuple(composition))
            # homogeneous batches (R concurrent lists of the SAME type +
            # permission — the common fleet shape) read R rows x one
            # shared window: promise the grid so the extraction is a
            # streamed dynamic_slice instead of an R x n random gather
            grid = None
            if len(set(composition)) == 1:
                off0, n0 = composition[0]
                grid = (off0, n0, len(composition))
            qfut = e._backend(cg).query_async(
                np.asarray(seeds, dtype=np.int32),
                np.concatenate(q_parts), np.concatenate(qb_parts),
                q_cache_key=key, q_contig_grid=grid)
        else:
            qfut = None
        observed = threading.Event()

        def materialize(pos, n, interner):
            out = qfut.result()  # QueryFuture memoizes; thread-safe reads
            if not observed.is_set():
                observed.set()
                metrics.histogram("engine_lookup_seconds").observe(
                    time.perf_counter() - t0)
                # fused dispatches deny missing-context conditional
                # grants fail-closed like every other path — they must
                # tick the same counter (once per dispatch, not per row)
                missing = getattr(qfut, "caveats_missing", lambda: 0)()
                if missing:
                    metrics.counter(
                        "engine_caveat_denied_missing_context_total"
                    ).inc(missing)
            return mask_pseudo_objects(np.array(out[pos:pos + n])), interner

        pos = 0
        for fut, interner, n in metas:
            if interner is None:
                fut._resolve(lambda: (None, None))
                continue
            fut._resolve(
                (lambda p, k, it: lambda: materialize(p, k, it))(
                    pos, n, interner))
            pos += n

    def close(self) -> None:
        """Flush the pending batch and mark the batcher dead: submits
        from here on bypass it entirely (direct engine path)."""
        with self._lock:
            self._closed = True
            batch = self._take_locked()
        if batch:
            self._flush(batch)
