"""Mutable relationship store: columnar, revisioned, watchable.

Plays the role of the reference's embedded SpiceDB datastore
(/root/reference/pkg/spicedb/spicedb.go:18-57): WriteRelationships with
CREATE/TOUCH/DELETE semantics and preconditions, ReadRelationships /
DeleteRelationships by filter, relationship expiration, and a watch log.

Layout is columnar int32 (see :class:`Columns`) so that 10M-relationship
graphs bulk-load and snapshot without per-row Python objects. The row-key
index the write path needs is hybrid (:class:`StoreIndex`): large chunks
(bulk loads) get a vectorized lexsorted packed-key index — built in
O(n log n) numpy, no per-row Python — while small write chunks land in a
plain dict; liveness is checked at lookup time so tombstoning a row needs
no index maintenance.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Iterator, Optional

import numpy as np

from .. import native
from ..models.tuples import Relationship
from ..utils.metrics import metrics
from .interning import Interner

# Operation codes (watch log + write ops)
OP_CREATE = 1
OP_TOUCH = 2
OP_DELETE = 3

_OPS = {"create": OP_CREATE, "touch": OP_TOUCH, "delete": OP_DELETE}

NO_EXPIRATION = np.float64(np.inf)


class StoreError(Exception):
    pass


class PreconditionFailed(StoreError):
    """A write's precondition did not hold (maps to gRPC FailedPrecondition,
    which the pessimistic workflow turns into kube 409 Conflict —
    reference workflow.go:189-202)."""


class AlreadyExists(StoreError):
    """CREATE of an existing relationship."""


@dataclass
class Columns:
    """Columnar relationship block: parallel int32 arrays + expiration
    + caveat-instance id (0 = unconditional; else an index into the
    store's append-only ``caveat_instances`` table)."""

    rt: np.ndarray  # resource type id      (types interner)
    rid: np.ndarray  # resource object id   (per-type objects interner)
    rl: np.ndarray  # relation id           (relations interner)
    st: np.ndarray  # subject type id
    sid: np.ndarray  # subject object id
    srl: np.ndarray  # subject relation id; 0 == none (ELLIPSIS)
    exp: np.ndarray  # float64 unix seconds; +inf == never expires
    cav: np.ndarray = None  # int32 caveat-instance id; 0 == none

    def __post_init__(self):
        if self.cav is None:
            self.cav = np.zeros(len(self.rt), dtype=np.int32)

    def __len__(self) -> int:
        return len(self.rt)

    @staticmethod
    def empty() -> "Columns":
        z = np.empty(0, dtype=np.int32)
        return Columns(z, z.copy(), z.copy(), z.copy(), z.copy(), z.copy(),
                       np.empty(0, dtype=np.float64), z.copy())

    @staticmethod
    def concat(blocks: list["Columns"]) -> "Columns":
        if not blocks:
            return Columns.empty()
        return Columns(*[
            np.concatenate([getattr(b, f) for b in blocks])
            for f in ("rt", "rid", "rl", "st", "sid", "srl", "exp", "cav")
        ])

    def take(self, idx) -> "Columns":
        return Columns(self.rt[idx], self.rid[idx], self.rl[idx], self.st[idx],
                       self.sid[idx], self.srl[idx], self.exp[idx],
                       self.cav[idx])


@dataclass(frozen=True)
class RelationshipFilter:
    """SpiceDB-style relationship filter. ``None`` fields match anything —
    the rules engine maps the ``$`` wildcard convention
    (reference pkg/authz/update.go:207-271) to ``None`` here."""

    resource_type: Optional[str] = None
    resource_id: Optional[str] = None
    relation: Optional[str] = None
    subject_type: Optional[str] = None
    subject_id: Optional[str] = None
    subject_relation: Optional[str] = None


@dataclass(frozen=True)
class Precondition:
    filter: RelationshipFilter
    must_exist: bool  # False => must NOT exist


@dataclass(frozen=True)
class WriteOp:
    op: str  # create | touch | delete
    rel: Relationship


@dataclass
class WatchRecord:
    revision: int
    op: int  # OP_TOUCH (covers create) | OP_DELETE
    rel: Relationship


@dataclass
class Snapshot:
    """Immutable view handed to the device compiler."""

    revision: int
    cols: Columns
    types: Interner
    relations: Interner
    objects: dict[int, Interner]  # type id -> per-type object interner
    # append-only (name, canonical ctx JSON) caveat-instance table;
    # index 0 reserved for "no caveat". Shared with the live store
    # (monotone like the interners), so sharing with an immutable
    # snapshot is safe.
    caveat_instances: list = field(default_factory=lambda: [("", "")])


# chunks at or above this many rows get the vectorized sorted index; below
# it a dict is faster to build and query
INDEX_SMALL_CHUNK = 4096

_MIX1 = np.uint64(0x9E3779B97F4A7C15)
_MIX2 = np.uint64(0xBF58476D1CE4E5B9)
_S29 = np.uint64(29)
_S32 = np.uint64(32)


def _hash_key_cols(rt, rid, rl, st, sid, srl) -> np.ndarray:
    """Vectorized 64-bit mix of the six key columns (splitmix-style).
    Collisions are verified against the actual columns at lookup, so the
    hash only needs good dispersion, not perfection. MUST stay arithmetic-
    identical to mix_key in native/graphcore.cpp — single-key lookups hash
    here against natively-built sorted arrays."""
    with np.errstate(over="ignore"):  # uint64 wraparound is the point
        h = np.asarray(rt).astype(np.uint64)
        for c in (rid, rl, st, sid, srl):
            h = (h ^ np.asarray(c).astype(np.uint64)) * _MIX1
            h = h ^ (h >> _S29)
        h = h * _MIX2
        return h ^ (h >> _S32)


class _SortedChunkIndex:
    """Vectorized index over one big chunk: row-key hashes argsorted once
    (O(n log n) numpy, no per-row Python), lookups by binary search with
    collision verification against the chunk columns."""

    __slots__ = ("hashes", "order", "cols")

    def __init__(self, cols: Columns):
        built = native.index_build(cols.rt, cols.rid, cols.rl,
                                   cols.st, cols.sid, cols.srl)
        if built is not None:  # multithreaded C++ hash + radix sort
            self.hashes, self.order = built
        else:
            h = _hash_key_cols(cols.rt, cols.rid, cols.rl,
                               cols.st, cols.sid, cols.srl)
            self.order = np.argsort(h)
            self.hashes = h[self.order]
        self.cols = cols

    def find(self, key: tuple) -> Optional[int]:
        h0 = _hash_key_cols(*key)
        lo = int(np.searchsorted(self.hashes, h0, side="left"))
        hi = int(np.searchsorted(self.hashes, h0, side="right"))
        c = self.cols
        rt, rid, rl, st, sid, srl = key
        for j in range(lo, hi):
            ri = int(self.order[j])
            if (c.rt[ri] == rt and c.rid[ri] == rid and c.rl[ri] == rl
                    and c.st[ri] == st and c.sid[ri] == sid
                    and c.srl[ri] == srl):
                return ri
        return None


class StoreIndex:
    """Hybrid row-key index. ``get`` returns the (chunk, row) of the LIVE
    row holding a key, or None — dead rows are filtered at lookup time, so
    tombstoning needs no index write. At most one live row per key exists
    (the store kills the old row before appending a replacement)."""

    def __init__(self):
        self._dict: dict[tuple, tuple[int, int]] = {}
        self._sorted: list[tuple[int, _SortedChunkIndex]] = []
        self._built = 0  # chunks indexed so far
        # chunk indexes computed ahead of time by a background thread
        # (keyed by chunk identity — chunks are immutable once appended)
        self._prebuilt: dict[int, _SortedChunkIndex] = {}
        self._prelock = threading.Lock()

    def prebuild(self, chunks: list[Columns]) -> None:
        """Build sorted indexes for not-yet-synced big chunks. Safe from a
        background thread: reads only immutable chunk arrays, publishes
        under its own lock, and never touches the synced state. Called by
        ``Store.bulk_load`` so the first write after a 10M-row load joins
        an already-running (usually finished) build instead of paying the
        full hash+radix-sort latency inline."""
        for cols in chunks[self._built:]:
            if len(cols) < INDEX_SMALL_CHUNK:
                continue
            key = id(cols)
            with self._prelock:
                if key in self._prebuilt:
                    continue
            idx = _SortedChunkIndex(cols)
            with self._prelock:
                self._prebuilt[key] = idx

    def sync(self, chunks: list[Columns]) -> None:
        for ci in range(self._built, len(chunks)):
            cols = chunks[ci]
            if len(cols) >= INDEX_SMALL_CHUNK:
                with self._prelock:
                    idx = self._prebuilt.pop(id(cols), None)
                self._sorted.append((ci, idx if idx is not None
                                     else _SortedChunkIndex(cols)))
            else:
                arr = np.stack([cols.rt, cols.rid, cols.rl, cols.st,
                                cols.sid, cols.srl], axis=1)
                for ri, row in enumerate(arr.tolist()):
                    self._dict[tuple(row)] = (ci, ri)
        self._built = len(chunks)

    def get(self, key: tuple, alive: list) -> Optional[tuple[int, int]]:
        pos = self._dict.get(key)
        if pos is not None and alive[pos[0]][pos[1]]:
            return pos
        for ci, idx in self._sorted:
            ri = idx.find(key)
            if ri is not None and alive[ci][ri]:
                return ci, ri
        return None


class Store:
    """Thread-safe mutable relationship store."""

    # Per-type object interners reserve index 0 for "void" (unknown ids at
    # query time) and 1 for the wildcard object '*'.
    RESERVED_OBJECTS = ("\x00void", "*")

    def __init__(self):
        self._lock = threading.RLock()
        # interner epoch: interners only ever APPEND within an epoch, so a
        # remote client may cache id->string tables keyed on (epoch, len)
        # and sync deltas; load() rebuilds the interners and MUST mint a
        # new epoch or cached mappings would silently alias new ids
        self.epoch = uuid.uuid4().hex
        self.types = Interner()
        # relation id 0 reserved for "no subject relation"
        self.relations = Interner(reserved=("",))
        self.objects: dict[int, Interner] = {}
        # caveat-instance table: one row per distinct (caveat name,
        # canonical context JSON) pair; append-only within an epoch so
        # snapshots/compiled graphs can share it by reference. Index 0
        # reserved for "no caveat".
        self.caveat_instances: list[tuple[str, str]] = [("", "")]
        self._caveat_key: dict[tuple, int] = {("", ""): 0}
        self._chunks: list[Columns] = []
        self._alive: list[np.ndarray] = []  # bool per chunk
        self._index = StoreIndex()
        self._prebuild_thread: Optional[threading.Thread] = None
        # revision-advance signal: wait_since() blocks on this instead of
        # polling, so watch consumers see writes at notify latency
        self._watch_cond = threading.Condition(self._lock)
        self.revision = 0
        # highest revision whose changes are NOT in the watch log
        # (bulk_load / snapshot restore) — incremental graph updates can
        # only start from revisions at or after this point
        self.unlogged_revision = 0
        self._watch_log: list[WatchRecord] = []
        # history retention: beyond the cap the oldest half is dropped and
        # watchers that far behind get a StoreError (re-list + re-watch,
        # kube "resourceVersion too old" semantics)
        self.watch_retention = 1_000_000
        self._watch_oldest_rev = 0
        # (revision, sorted unique finite expirations of live rows): the
        # decision cache's expiration watermark, rebuilt lazily at most
        # once per revision (engine/decision_cache.py). _has_finite_exp
        # is the monotone fast path: stores that never wrote an expiring
        # tuple (the common deployment) skip the rebuild scan entirely.
        self._expiry_bounds: Optional[tuple] = None
        self._has_finite_exp = False
        # durability hook (persistence/manager.py): called UNDER the
        # write lock with (record_meta, blob) after each revision-
        # advancing mutation, so journal order == revision order and the
        # record is on disk before the transaction returns. None = the
        # store is purely in-memory (default; every existing caller).
        self.journal = None

    # -- interning helpers -------------------------------------------------

    def _obj_interner(self, type_id: int) -> Interner:
        it = self.objects.get(type_id)
        if it is None:
            it = Interner(reserved=self.RESERVED_OBJECTS)
            self.objects[type_id] = it
        return it

    def _intern_rel(self, rel: Relationship) -> tuple:
        rt = self.types.intern(rel.resource_type)
        st = self.types.intern(rel.subject_type)
        return (
            rt,
            self._obj_interner(rt).intern(rel.resource_id),
            self.relations.intern(rel.relation),
            st,
            self._obj_interner(st).intern(rel.subject_id),
            self.relations.intern(rel.subject_relation or ""),
        )

    def _intern_cav(self, rel: Relationship) -> int:
        """Caveat-instance id for a relationship (0 = unconditional)."""
        if not rel.caveat:
            return 0
        k = (rel.caveat, rel.caveat_context or "")
        i = self._caveat_key.get(k)
        if i is None:
            i = len(self.caveat_instances)
            self.caveat_instances.append(k)
            self._caveat_key[k] = i
        return i

    def _extern_rel(self, key: tuple, exp: float,
                    cav: int = 0) -> Relationship:
        rt, rid, rl, st, sid, srl = key
        name, ctx = self.caveat_instances[cav] if cav else ("", "")
        return Relationship(
            self.types.string(rt),
            self.objects[rt].string(rid),
            self.relations.string(rl),
            self.types.string(st),
            self.objects[st].string(sid),
            self.relations.string(srl) or None,
            None if not np.isfinite(exp) else float(exp),
            name or None,
            ctx or None,
        )

    # -- index -------------------------------------------------------------

    def _ensure_index(self) -> StoreIndex:
        t = self._prebuild_thread
        if t is not None:
            if t.is_alive():
                t.join()
            self._prebuild_thread = None
        self._index.sync(self._chunks)
        return self._index

    def _start_index_prebuild(self) -> None:
        """Overlap the big-chunk index build with whatever follows a bulk
        load (graph compile takes ~12s at 10M rows; the build ~1.5s)."""
        prev = self._prebuild_thread
        if prev is not None and prev.is_alive():
            # back-to-back bulk loads: an abandoned thread could publish a
            # stale _prebuilt entry after sync() already passed its chunk,
            # pinning the sorted index (and the chunk) forever
            prev.join()
        idx, chunks = self._index, list(self._chunks)
        t = threading.Thread(target=idx.prebuild, args=(chunks,),
                             daemon=True, name="store-index-prebuild")
        self._prebuild_thread = t
        t.start()

    def _append_rows(self, cols: Columns) -> None:
        # the index picks the new chunk up at the next sync (lazy)
        self._chunks.append(cols)
        self._alive.append(np.ones(len(cols), dtype=bool))

    # -- filter matching ---------------------------------------------------

    def _filter_mask(self, cols: Columns, f: RelationshipFilter,
                     now: Optional[float] = None) -> np.ndarray:
        mask = np.ones(len(cols), dtype=bool)

        def match_str(interner: Interner, col: np.ndarray, value: Optional[str]):
            nonlocal mask
            if value is None:
                return
            i = interner.lookup(value)
            if i is None:
                mask &= False
            else:
                mask &= col == i

        match_str(self.types, cols.rt, f.resource_type)
        match_str(self.relations, cols.rl, f.relation)
        match_str(self.types, cols.st, f.subject_type)
        if f.resource_id is not None or f.subject_id is not None or \
           f.subject_relation is not None:
            # object ids live in per-type interners; resolve per present type
            if f.resource_id is not None:
                ok = np.zeros(len(cols), dtype=bool)
                for tid in np.unique(cols.rt[mask]).tolist():
                    oi = self.objects.get(tid)
                    v = oi.lookup(f.resource_id) if oi else None
                    if v is not None:
                        ok |= (cols.rt == tid) & (cols.rid == v)
                mask &= ok
            if f.subject_id is not None:
                ok = np.zeros(len(cols), dtype=bool)
                for tid in np.unique(cols.st[mask]).tolist():
                    oi = self.objects.get(tid)
                    v = oi.lookup(f.subject_id) if oi else None
                    if v is not None:
                        ok |= (cols.st == tid) & (cols.sid == v)
                mask &= ok
            if f.subject_relation is not None:
                i = self.relations.lookup(f.subject_relation)
                mask &= (cols.srl == i) if i is not None else False
        if now is not None:
            mask &= cols.exp > now
        return mask

    # -- public API --------------------------------------------------------

    def _observe_revision(self) -> None:
        """Observability gauges, refreshed by EVERY revision-advancing
        mutation (write, delete, bulk load, state install/restore):
        revision for cache-key/trace correlation, watch-log depth for
        follower catch-up headroom."""
        metrics.gauge("store_revision").set(self.revision)
        metrics.gauge("store_watch_log_records").set(len(self._watch_log))

    def write(self, ops: list[WriteOp],
              preconditions: list[Precondition] = ()) -> int:
        """Apply a write transaction; returns the new revision.

        CREATE errors on an existing live tuple (SpiceDB AlreadyExists);
        TOUCH upserts (refreshing expiration); DELETE is idempotent — the
        reference's rollback inverts CREATE/TOUCH into DELETE and retries
        until success (workflow.go:86-129), which requires idempotency.
        """
        t0 = time.perf_counter()
        with self._lock:
            now = time.time()
            for pc in preconditions:
                if self.exists(pc.filter, _now=now) != pc.must_exist:
                    raise PreconditionFailed(
                        f"precondition {'exists' if pc.must_exist else 'does not exist'} "
                        f"failed for {pc.filter}"
                    )
            idx = self._ensure_index()

            # Pass 1 — plan + validate before any mutation so the whole
            # batch is atomic: an AlreadyExists mid-batch must not leave
            # earlier ops half-applied. Like SpiceDB, duplicate updates for
            # the same tuple within one write are rejected, so the plan is
            # order-free.
            seen: set[tuple] = set()
            plan: list[tuple[int, tuple, float, int]] = []
            for wop in ops:
                code = _OPS[wop.op]
                key = self._intern_rel(wop.rel)
                exp = wop.rel.expiration if wop.rel.expiration is not None \
                    else NO_EXPIRATION
                if key in seen:
                    raise StoreError(
                        f"duplicate update for relationship in one write: {wop.rel}"
                    )
                seen.add(key)
                pos = idx.get(key, self._alive)
                live = pos is not None and bool(
                    self._chunks[pos[0]].exp[pos[1]] > now
                )
                if code == OP_CREATE and live:
                    raise AlreadyExists(f"relationship already exists: {wop.rel}")
                if code == OP_DELETE:
                    if pos is not None:  # tombstone even expired rows
                        plan.append((OP_DELETE, key, NO_EXPIRATION, 0))
                    continue
                plan.append((OP_TOUCH, key, float(exp),
                             self._intern_cav(wop.rel)))

            if not plan:
                return self.revision

            # Pass 2 — apply.
            rev = self.revision + 1
            new_rows: list[tuple[tuple, float, int]] = []
            journaled = self.journal is not None
            effects: list[dict] = []  # journal record (concrete, replayable)
            for code, key, exp, cav in plan:
                pos = idx.get(key, self._alive)
                if pos is not None:
                    self._alive[pos[0]][pos[1]] = False
                if code == OP_DELETE:
                    rel = self._extern_rel(key, NO_EXPIRATION)
                    self._watch_log.append(
                        WatchRecord(rev, OP_DELETE, rel))
                    if journaled:
                        effects.append({"op": "delete", "rel": asdict(rel)})
                    continue
                new_rows.append((key, exp, cav))
                rel = self._extern_rel(key, exp, cav)
                self._watch_log.append(WatchRecord(rev, OP_TOUCH, rel))
                if journaled:
                    effects.append({"op": "touch", "rel": asdict(rel)})
            if new_rows:
                keys = np.array([k for k, _, _ in new_rows], dtype=np.int32)
                exp_col = np.array([e for _, e, _ in new_rows],
                                   dtype=np.float64)
                cav_col = np.array([c for _, _, c in new_rows],
                                   dtype=np.int32)
                cols = Columns(
                    keys[:, 0].copy(), keys[:, 1].copy(), keys[:, 2].copy(),
                    keys[:, 3].copy(), keys[:, 4].copy(), keys[:, 5].copy(),
                    exp_col, cav_col,
                )
                self._append_rows(cols)
                if not self._has_finite_exp and np.isfinite(exp_col).any():
                    self._has_finite_exp = True
            self._trim_watch_log()
            self.revision = rev
            self._observe_revision()
            if self.journal is not None:
                self.journal({"kind": "write", "rev": rev,
                              "effects": effects}, None)
            self._watch_cond.notify_all()
            # the journal/index share of one applied write transaction —
            # the "journal" stage of the per-write breakdown (the overlay
            # append and read dispatch are timed by their own layers)
            metrics.histogram("store_write_seconds").observe(
                time.perf_counter() - t0)
            return rev

    def bulk_load(self, rels_cols: dict,
                  _revision: Optional[int] = None) -> int:
        """Fast path for large graph loads (bench setup): columnar string
        arrays {resource_type, resource_id, relation, subject_type,
        subject_id, subject_relation?, expiration?}. Rows are assumed
        deduplicated. Not logged to watch. ``_revision`` pins the
        assigned revision — the WAL replay path (persistence/recovery.py)
        re-applies a journaled load at the revision it was acknowledged
        with."""
        with self._lock:
            if _revision is not None and _revision <= self.revision:
                raise StoreError(
                    f"bulk_load replay revision {_revision} is not past "
                    f"current revision {self.revision}")
            n = len(rels_cols["resource_id"])

            def intern_typed(type_col, id_col):
                tids = self.types.intern_many(type_col)
                # pass ndarrays through unchanged (fixed-width columns feed
                # the native hash-unique zero-copy); lists become object
                # arrays to avoid 4*maxlen-per-element unicode inflation
                ids = (id_col if isinstance(id_col, np.ndarray)
                       else np.asarray(id_col, dtype=object))
                out = np.empty(n, dtype=np.int32)
                for tid in np.unique(tids).tolist():
                    sel = tids == tid
                    out[sel] = self._obj_interner(int(tid)).intern_many(
                        ids[sel]
                    )
                return tids, out

            rt, rid = intern_typed(rels_cols["resource_type"],
                                   rels_cols["resource_id"])
            st, sid = intern_typed(rels_cols["subject_type"],
                                   rels_cols["subject_id"])
            rl = self.relations.intern_many(rels_cols["relation"])
            srl_col = rels_cols.get("subject_relation")
            srl = (self.relations.intern_many(srl_col) if srl_col is not None
                   else np.zeros(n, dtype=np.int32))
            exp_col = rels_cols.get("expiration")
            exp = (np.asarray(exp_col, dtype=np.float64) if exp_col is not None
                   else np.full(n, NO_EXPIRATION))
            exp = np.where(np.isnan(exp), NO_EXPIRATION, exp)
            cav_name_col = rels_cols.get("caveat")
            if cav_name_col is not None:
                from ..models.tuples import canonical_context

                names = np.asarray(cav_name_col, dtype=str)
                ctx_col = rels_cols.get("caveat_context")
                ctxs = (np.asarray(ctx_col, dtype=str)
                        if ctx_col is not None
                        else np.full(n, "", dtype=str))
                # dedup (name, ctx) pairs vectorized before interning:
                # a 30%-caveated 10M-row load carries a handful of
                # distinct contexts, not 3M. ':' cannot appear in a
                # caveat NAME (identifier charset), so the first ':'
                # splits unambiguously (NUL would truncate numpy
                # fixed-width unicode arrays)
                combo = np.char.add(np.char.add(names, ":"), ctxs)
                uniq, inv = np.unique(combo, return_inverse=True)
                codes = np.empty(len(uniq), dtype=np.int32)
                for i, u in enumerate(uniq.tolist()):
                    nm, _, cx = u.partition(":")
                    if not nm:
                        codes[i] = 0
                        continue
                    codes[i] = self._intern_cav(Relationship(
                        "", "", "", "", "", None, None, nm,
                        canonical_context(cx)))
                cav = codes[inv]
            else:
                cav = np.zeros(n, dtype=np.int32)
            self._append_rows(Columns(rt, rid, rl, st, sid, srl, exp, cav))
            if not self._has_finite_exp and np.isfinite(exp).any():
                self._has_finite_exp = True
            self.revision = (_revision if _revision is not None
                             else self.revision + 1)
            self.unlogged_revision = self.revision
            self._observe_revision()
            if self.journal is not None:
                from ..persistence.codec import encode_bulk_cols

                self.journal({"kind": "bulk_load", "rev": self.revision},
                             encode_bulk_cols(rels_cols))
            self._watch_cond.notify_all()
            self._start_index_prebuild()
            return self.revision

    def read(self, f: RelationshipFilter, now: Optional[float] = None
             ) -> list[Relationship]:
        """ReadRelationships: live, unexpired tuples matching the filter.
        Materialized under the lock (a lazily-consumed generator would hold
        the store lock across yields and deadlock writers)."""
        with self._lock:
            if now is None:
                now = time.time()
            out: list[Relationship] = []
            for cols, alive in zip(self._chunks, self._alive):
                mask = self._filter_mask(cols, f, now=now) & alive
                for ri in np.flatnonzero(mask).tolist():
                    key = (int(cols.rt[ri]), int(cols.rid[ri]), int(cols.rl[ri]),
                           int(cols.st[ri]), int(cols.sid[ri]), int(cols.srl[ri]))
                    out.append(self._extern_rel(key, cols.exp[ri],
                                                int(cols.cav[ri])))
            return out

    def exists(self, f: RelationshipFilter, _now: Optional[float] = None) -> bool:
        with self._lock:
            now = _now if _now is not None else time.time()
            for cols, alive in zip(self._chunks, self._alive):
                if np.any(self._filter_mask(cols, f, now=now) & alive):
                    return True
            return False

    def delete_by_filter(self, f: RelationshipFilter,
                         preconditions: list[Precondition] = ()) -> int:
        """DeleteRelationships: delete all matching tuples; returns count.
        Preconditions are checked under the same lock acquisition as the
        delete so they cannot be invalidated in between."""
        with self._lock:
            now = time.time()
            for pc in preconditions:
                if self.exists(pc.filter, _now=now) != pc.must_exist:
                    raise PreconditionFailed(
                        f"precondition "
                        f"{'exists' if pc.must_exist else 'does not exist'} "
                        f"failed for {pc.filter}"
                    )
            count = 0
            rev = self.revision + 1
            journaled = self.journal is not None
            effects: list[dict] = []
            for cols, alive in zip(self._chunks, self._alive):
                mask = self._filter_mask(cols, f, now=now) & alive
                rows = np.flatnonzero(mask)
                if len(rows) == 0:
                    continue
                alive[rows] = False
                count += len(rows)
                for ri in rows.tolist():
                    key = (int(cols.rt[ri]), int(cols.rid[ri]), int(cols.rl[ri]),
                           int(cols.st[ri]), int(cols.sid[ri]), int(cols.srl[ri]))
                    # the index needs no touch-up: lookups check aliveness
                    rel = self._extern_rel(key, NO_EXPIRATION)
                    self._watch_log.append(WatchRecord(rev, OP_DELETE, rel))
                    if journaled:
                        effects.append({"op": "delete", "rel": asdict(rel)})
            if count:
                self._trim_watch_log()
                self.revision = rev
                self._observe_revision()
                if self.journal is not None:
                    self.journal({"kind": "delete", "rev": rev,
                                  "effects": effects}, None)
                self._watch_cond.notify_all()
            return count

    def apply_effects(self, effects: list, revision: int) -> None:
        """Replay hook: apply concrete touch/delete effects and pin the
        revision. Two callers — WAL replay at boot (persistence/
        recovery.py) and follower catch-up over the mirror protocol
        (parallel/multihost.py) — both re-applying decisions a live
        ``write``/``delete_by_filter`` already made, so there are no
        preconditions, no duplicate checks, and no clock reads here.
        Within one call the LAST effect per key wins (a catch-up batch
        spans many revisions; the store jumps straight to the final
        state). Nothing lands in the watch log: replayed history is a new
        lineage for watchers (same contract as a snapshot restore), and
        ``unlogged_revision`` advances so incremental graph updates
        restart from the recovered point."""
        with self._lock:
            revision = int(revision)
            if revision <= self.revision:
                raise StoreError(
                    f"apply_effects revision {revision} is not past "
                    f"current revision {self.revision}")
            idx = self._ensure_index()
            final: dict[tuple, Optional[tuple]] = {}
            journaled: list[dict] = []
            for eff in effects:
                rel = eff["rel"]
                if isinstance(rel, dict):
                    rel = Relationship(**rel)
                key = self._intern_rel(rel)
                if eff["op"] == "delete":
                    final[key] = None
                else:
                    final[key] = ((float(rel.expiration)
                                   if rel.expiration is not None
                                   else float(NO_EXPIRATION)),
                                  self._intern_cav(rel))
                journaled.append({"op": eff["op"], "rel": asdict(rel)})
            new_rows: list[tuple[tuple, float, int]] = []
            for key, ent in final.items():
                pos = idx.get(key, self._alive)
                if pos is not None:
                    self._alive[pos[0]][pos[1]] = False
                if ent is not None:
                    new_rows.append((key, ent[0], ent[1]))
            if new_rows:
                keys = np.array([k for k, _, _ in new_rows], dtype=np.int32)
                exp_col = np.array([e for _, e, _ in new_rows],
                                   dtype=np.float64)
                cav_col = np.array([c for _, _, c in new_rows],
                                   dtype=np.int32)
                self._append_rows(Columns(
                    keys[:, 0].copy(), keys[:, 1].copy(), keys[:, 2].copy(),
                    keys[:, 3].copy(), keys[:, 4].copy(), keys[:, 5].copy(),
                    exp_col, cav_col,
                ))
                if not self._has_finite_exp and np.isfinite(exp_col).any():
                    self._has_finite_exp = True
            self._expiry_bounds = None
            self.revision = revision
            self.unlogged_revision = revision
            self._observe_revision()
            # watchers from before the jump must re-list (their revisions
            # describe history this store never logged) — same contract
            # as a snapshot restore
            self._watch_oldest_rev = revision
            if self.journal is not None:
                self.journal({"kind": "apply", "rev": revision,
                              "effects": journaled}, None)
            self._watch_cond.notify_all()

    def next_expiry(self, now: float) -> float:
        """Earliest expiration boundary strictly after ``now`` among live
        tuples — the decision cache's per-snapshot validity watermark:
        a result computed at ``now`` stays exact until this instant (the
        clock cannot revoke or grant anything in between; writes bump the
        revision and change the cache key instead). ``+inf`` when no live
        tuple carries a finite expiration.

        Cheap: stores that never wrote an expiring tuple answer from a
        flag without touching a row; otherwise the sorted boundary array
        is rebuilt at most once per revision (lazily, on first ask) and
        each call is a binary search."""
        with self._lock:
            if not self._has_finite_exp:
                return float("inf")
            ent = self._expiry_bounds
            if ent is None or ent[0] != self.revision:
                vals = []
                for cols, alive in zip(self._chunks, self._alive):
                    sel = alive & np.isfinite(cols.exp)
                    if sel.any():
                        vals.append(cols.exp[sel])
                arr = (np.unique(np.concatenate(vals)) if vals
                       else np.empty(0, dtype=np.float64))
                self._expiry_bounds = ent = (self.revision, arr)
            arr = ent[1]
            i = int(np.searchsorted(arr, now, side="right"))
            return float(arr[i]) if i < len(arr) else float("inf")

    def _trim_watch_log(self) -> None:
        # caller holds the lock
        if len(self._watch_log) > self.watch_retention:
            drop = len(self._watch_log) // 2
            self._watch_oldest_rev = self._watch_log[drop - 1].revision
            del self._watch_log[:drop]

    def wake_waiters(self) -> None:
        """Release every thread parked in :meth:`wait_since` (they return
        ``[]``). Shutdown paths call this so a drain never has to wait
        out a wait timeout."""
        with self._watch_cond:
            self._watch_cond.notify_all()

    def wait_since(self, revision: int, timeout: float) -> list[WatchRecord]:
        """Block until events past ``revision`` exist (or ``timeout``
        elapses — then ``[]``), and return them. Push-latency watch
        consumption: one waiting thread per hub, zero polling."""
        with self._watch_cond:
            if revision > self.revision:
                # from-the-future guard (see watch_since): never park a
                # stale-lineage watcher until the numbers happen to
                # overlap — it would silently miss the whole window
                return self.watch_since(revision)
            if self.revision <= revision:
                self._watch_cond.wait(timeout)
            if self.revision <= revision:
                return []
            return self.watch_since(revision)

    def watch_since(self, revision: int) -> list[WatchRecord]:
        """Watch events with revision > the given revision. Binary-searched
        (records are appended in revision order); raises if the requested
        revision predates the retained history — or runs AHEAD of it: a
        revision from the future can only come from a superseded lineage
        (a leader-failover rebase can move this store to a LOWER revision
        than the one it served before), and blocking until the new
        lineage's numbers catch up would silently skip every event in
        the overlap, revocations included."""
        with self._lock:
            if revision > self.revision:
                raise StoreError(
                    f"watch revision {revision} is ahead of the store "
                    f"(revision {self.revision}); the watched lineage "
                    "was superseded — re-list and re-watch")
            if revision < self._watch_oldest_rev:
                raise StoreError(
                    f"watch history before revision {self._watch_oldest_rev} "
                    "has been trimmed; re-list and re-watch"
                )
            import bisect

            i = bisect.bisect_right(
                self._watch_log, revision, key=lambda r: r.revision
            )
            return self._watch_log[i:]

    # -- durability ---------------------------------------------------------

    def _collect_state(self) -> tuple["Columns", dict]:
        """(compacted live columns, meta) under the lock — the snapshot
        payload shared by file saves and the follower full-state wire
        transfer."""
        with self._lock:
            live = [cols.take(np.flatnonzero(alive))
                    for cols, alive in zip(self._chunks, self._alive)
                    if np.any(alive)]
            cols = Columns.concat(live)
            meta = {
                "revision": self.revision,
                "types": self.types.strings(),
                "relations": self.relations.strings(),
                "objects": {str(tid): it.strings()
                            for tid, it in self.objects.items()},
                "caveat_instances": [list(p)
                                     for p in self.caveat_instances],
            }
        return cols, meta

    def save(self, path: str) -> int:
        """Persist the store to one compressed npz: live rows compacted
        into a single chunk plus the interner string tables; returns the
        saved revision (the checkpointer stamps it into the snapshot file
        name). The watch log is NOT persisted — a watcher resuming
        against a restored store gets the kube "resourceVersion too old"
        treatment (re-list + re-watch), the same contract as crossing the
        in-memory retention horizon."""
        import json
        import os

        cols, meta = self._collect_state()
        import tempfile

        # unique temp per save (mkstemp, not pid-keyed: concurrent saves in
        # one process must not truncate each other), streamed directly (no
        # in-memory archive copy), then published atomically
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)),
            prefix=os.path.basename(path) + ".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(
                    f, rt=cols.rt, rid=cols.rid, rl=cols.rl, st=cols.st,
                    sid=cols.sid, srl=cols.srl, exp=cols.exp,
                    cav=cols.cav,
                    meta=np.frombuffer(json.dumps(meta).encode(),
                                       dtype=np.uint8),
                )
                # data blocks must be durable BEFORE the rename publishes
                # the file: the checkpointer prunes WAL segments on the
                # strength of this snapshot existing, and a power loss
                # must not leave a directory entry pointing at page cache
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return int(meta["revision"])

    @staticmethod
    def encode_state(cols: "Columns", meta: dict) -> bytes:
        """Serialize a ``_collect_state`` pair to the snapshot npz
        format. Static and lock-free on purpose: the collected arrays
        are immutable copies, so a caller holding ordering-critical
        locks (the mirror lock during follower catch-up) can collect
        under the lock and pay the compression outside it."""
        import io
        import json

        bio = io.BytesIO()
        np.savez_compressed(
            bio, rt=cols.rt, rid=cols.rid, rl=cols.rl, st=cols.st,
            sid=cols.sid, srl=cols.srl, exp=cols.exp, cav=cols.cav,
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        )
        return bio.getvalue()

    def state_bytes(self) -> tuple[int, bytes]:
        """(revision, full-state payload): the save() npz, in memory —
        the leader->follower catch-up transfer when the follower's
        resume revision predates the leader's retained watch history
        (engine/remote.py mirror_subscribe from_revision)."""
        cols, meta = self._collect_state()
        return int(meta["revision"]), self.encode_state(cols, meta)

    @staticmethod
    def _parse_state(z) -> tuple[dict, "Columns"]:
        import json

        # np.asarray instead of astype: matching-dtype columns pass
        # through without a copy, which keeps mmap-backed directory
        # snapshots (load(..., mmap=True)) lazily paged instead of
        # materializing a second full copy at parse time
        names = z.files if hasattr(z, "files") else set(z.keys())
        meta = json.loads(bytes(np.asarray(z["meta"]).tobytes()).decode())
        cols = Columns(
            np.asarray(z["rt"], dtype=np.int32),
            np.asarray(z["rid"], dtype=np.int32),
            np.asarray(z["rl"], dtype=np.int32),
            np.asarray(z["st"], dtype=np.int32),
            np.asarray(z["sid"], dtype=np.int32),
            np.asarray(z["srl"], dtype=np.int32),
            np.asarray(z["exp"], dtype=np.float64),
            # snapshots predating caveat support carry no cav column:
            # every restored tuple is unconditional
            (np.asarray(z["cav"], dtype=np.int32)
             if "cav" in names else None),
        )
        return meta, cols

    def save_dir(self, path: str) -> int:
        """Save a snapshot in the ``persistence/codec.save`` directory
        form (one flat ``.npy`` per column): the only layout
        ``load(..., mmap=True)`` can genuinely memory-map back.
        Returns the saved revision."""
        import json

        from ..persistence import codec

        cols, meta = self._collect_state()
        arrays = {
            "rt": cols.rt, "rid": cols.rid, "rl": cols.rl,
            "st": cols.st, "sid": cols.sid, "srl": cols.srl,
            "exp": cols.exp, "cav": cols.cav,
            "meta": np.frombuffer(json.dumps(meta).encode(),
                                  dtype=np.uint8),
        }
        codec.save(path, {k: v for k, v in arrays.items()
                          if v is not None})
        return int(meta["revision"])

    def load(self, path: str, mmap: bool = False) -> None:
        """Replace this store's contents with a saved snapshot.

        ``path`` is either the classic single-file npz or a
        :meth:`save_dir` directory; the directory form with
        ``mmap=True`` maps every column read-only so restoring a large
        graph pages tuples in on demand instead of transiently holding
        snapshot + store copies in host RAM at once (npz/zip members
        cannot be mmapped — see persistence/codec.load)."""
        import os

        if os.path.isdir(path):
            from ..persistence import codec

            meta, cols = self._parse_state(codec.load(path, mmap=mmap))
        else:
            with np.load(path) as z:
                meta, cols = self._parse_state(z)
        self._install_state(meta, cols)

    def load_state_bytes(self, payload: bytes) -> None:
        """Replace this store's contents from a :meth:`state_bytes`
        payload (follower full-state catch-up). Journaled as a
        ``load_state`` record so a follower restart recovers the
        transferred baseline too."""
        import io

        with np.load(io.BytesIO(payload)) as z:
            meta, cols = self._parse_state(z)
        self._install_state(meta, cols, journal_payload=payload)

    def _install_state(self, meta: dict, cols: "Columns",
                       journal_payload: Optional[bytes] = None) -> None:
        with self._lock:
            self.epoch = uuid.uuid4().hex  # cached id maps are now invalid
            self.types = Interner()
            for s in meta["types"]:
                self.types.intern(s)
            self.relations = Interner()
            for s in meta["relations"]:
                self.relations.intern(s)
            self.objects = {}
            for tid, strings in meta["objects"].items():
                it = Interner()
                for s in strings:
                    it.intern(s)
                self.objects[int(tid)] = it
            insts = meta.get("caveat_instances") or [["", ""]]
            self.caveat_instances = [tuple(p) for p in insts]
            self._caveat_key = {tuple(p): i
                                for i, p in enumerate(insts)}
            self._chunks = [cols]
            self._alive = [np.ones(len(cols), dtype=bool)]
            self._index = StoreIndex()
            self._start_index_prebuild()
            # a restored store may land on the SAME revision number with
            # different rows — the revision check alone would serve the
            # old lineage's expiration watermark
            self._expiry_bounds = None
            self._has_finite_exp = bool(np.isfinite(cols.exp).any())
            self.revision = int(meta["revision"])
            self.unlogged_revision = self.revision
            self._watch_log = []
            self._observe_revision()
            # watchers from before the restore must re-list + re-watch
            # (their revisions describe a different store lineage) — make
            # watch_since raise instead of silently returning no events
            self._watch_oldest_rev = self.revision
            if self.journal is not None and journal_payload is not None:
                self.journal({"kind": "load_state", "rev": self.revision},
                             journal_payload)
            self._watch_cond.notify_all()

    def snapshot(self) -> Snapshot:
        """Immutable columnar view of all live tuples for the compiler.

        Expired tuples are retained (with their timestamps) — the device
        kernel masks them against the query-time clock, mirroring SpiceDB's
        read-time expiration filtering."""
        with self._lock:
            blocks = [
                cols.take(np.flatnonzero(alive))
                for cols, alive in zip(self._chunks, self._alive)
                if np.any(alive)
            ]
            # NOTE: interners are monotone (never shrink / renumber), so
            # sharing them with an immutable snapshot is safe.
            return Snapshot(
                revision=self.revision,
                cols=Columns.concat(blocks),
                types=self.types,
                relations=self.relations,
                objects=self.objects,
                caveat_instances=self.caveat_instances,
            )

    def __len__(self) -> int:
        return int(sum(int(a.sum()) for a in self._alive))
