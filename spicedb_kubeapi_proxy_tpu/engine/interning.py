"""String interning: stable string -> int32 ids, vectorized for bulk loads."""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


class Interner:
    """Monotone string→int table. Index 0 is reserved for ``reserved[0]``, etc.

    Used for type names, relation names, and per-type object ids. Bulk
    interning goes through :meth:`intern_many` (one dict pass, no per-call
    Python overhead beyond the loop).
    """

    __slots__ = ("_to_id", "_to_str")

    def __init__(self, reserved: Iterable[str] = ()):
        self._to_id: dict[str, int] = {}
        self._to_str: list[str] = []
        for s in reserved:
            self.intern(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def lookup(self, s: str) -> Optional[int]:
        return self._to_id.get(s)

    def string(self, i: int) -> str:
        return self._to_str[i]

    def intern_many(self, strings) -> np.ndarray:
        """Intern a sequence of strings, returning int32 ids.

        Vectorized for bulk loads: one ``np.unique`` pass over the column,
        then a Python loop only over the (typically tiny) vocabulary. New
        ids are assigned in sorted-unique order rather than first-occurrence
        order — callers never depend on id assignment order.
        """
        n = len(strings)
        if n == 0:
            return np.empty(0, dtype=np.int32)
        if n > 1024:
            # object dtype keeps elements pointer-sized; a fixed-width
            # unicode array would cost 4*maxlen bytes per element (one long
            # outlier id would blow up a 10M-row column)
            arr = np.asarray(strings, dtype=object)
            uniq, inv = np.unique(arr, return_inverse=True)
            ids = np.fromiter(
                (self.intern(s) for s in uniq.tolist()),
                dtype=np.int32, count=len(uniq),
            )
            return ids[inv.reshape(-1)]
        to_id = self._to_id
        to_str = self._to_str
        out = np.empty(n, dtype=np.int32)
        for k, s in enumerate(strings):
            i = to_id.get(s)
            if i is None:
                i = len(to_str)
                to_id[s] = i
                to_str.append(s)
            out[k] = i
        return out

    def strings(self) -> list[str]:
        return list(self._to_str)
