"""String interning: stable string -> int32 ids, vectorized for bulk loads."""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from .. import native


def _as_str(s) -> str:
    """Every path interns str keys: bytes columns ('S' dtype, or bytes
    elements in lists) decode identically whether they ride the native
    hash-unique, np.unique, or the small-column dict loop. surrogateescape
    keeps non-UTF8 bytes deterministic instead of raising on one path."""
    return s.decode(errors="surrogateescape") if isinstance(s, bytes) else s


class Interner:
    """Monotone string→int table. Index 0 is reserved for ``reserved[0]``, etc.

    Used for type names, relation names, and per-type object ids. Bulk
    interning goes through :meth:`intern_many` (one dict pass, no per-call
    Python overhead beyond the loop).
    """

    __slots__ = ("_to_id", "_to_str")

    def __init__(self, reserved: Iterable[str] = ()):
        self._to_id: dict[str, int] = {}
        self._to_str: list[str] = []
        for s in reserved:
            self.intern(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def lookup(self, s: str) -> Optional[int]:
        return self._to_id.get(s)

    def string(self, i: int) -> str:
        return self._to_str[i]

    def intern_many(self, strings) -> np.ndarray:
        """Intern a sequence of strings, returning int32 ids.

        Vectorized for bulk loads: one ``np.unique`` pass over the column,
        then a Python loop only over the (typically tiny) vocabulary. New
        ids are assigned in sorted-unique order rather than first-occurrence
        order — callers never depend on id assignment order.
        """
        n = len(strings)
        if n == 0:
            return np.empty(0, dtype=np.int32)
        if n > 1024:
            # object dtype keeps elements pointer-sized; a fixed-width
            # unicode array would cost 4*maxlen bytes per element (one long
            # outlier id would blow up a 10M-row column). Columns that are
            # ALREADY fixed-width numpy arrays keep their layout — that is
            # the zero-copy input to the native hash-unique.
            arr = (strings if isinstance(strings, np.ndarray)
                   else np.asarray(strings, dtype=object))
            res = None
            if arr.ndim == 1 and arr.dtype.kind in "SU":
                barr = arr
                if arr.dtype.kind == "U":
                    try:
                        barr = arr.astype("S")
                    except UnicodeEncodeError:
                        barr = None
                if barr is not None and barr.dtype.itemsize:
                    res = native.unique_inverse(barr)
            if res is not None:
                uniq_rows, inv = res
                uniq = arr[uniq_rows]
                ids = np.fromiter(
                    (self.intern(_as_str(s)) for s in uniq.tolist()),
                    dtype=np.int32, count=len(uniq_rows),
                )
                return ids[inv]
            uniq, inv = np.unique(arr, return_inverse=True)
            ids = np.fromiter(
                (self.intern(_as_str(s)) for s in uniq.tolist()),
                dtype=np.int32, count=len(uniq),
            )
            return ids[inv.reshape(-1)]
        to_id = self._to_id
        to_str = self._to_str
        out = np.empty(n, dtype=np.int32)
        for k, s in enumerate(strings):
            s = _as_str(s)
            i = to_id.get(s)
            if i is None:
                i = len(to_str)
                to_id[s] = i
                to_str.append(s)
            out[k] = i
        return out

    def id_map(self) -> dict:
        """The live string→id dict, for hot loops that inline lookups
        (engine check-batch encode). Callers must treat it as read-only."""
        return self._to_id

    def strings(self) -> list[str]:
        return list(self._to_str)
