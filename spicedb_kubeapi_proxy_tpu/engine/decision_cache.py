"""Revision-keyed authorization decision cache with singleflight dedup.

The serving-curve observation (ISSUE 2 / Samyama arxiv 2603.08036,
RedisGraph arxiv 1905.01294): repeat-heavy traffic — watch fan-out,
dashboard polling, fleet-wide lists by the same service account — pays a
full slot-space fixpoint dispatch per request even when the query is
byte-identical to one answered microseconds ago at the same store
revision. This layer turns that into O(distinct queries per revision)
device dispatches:

- **Cache**: a sharded-lock LRU keyed by ``(kind, store revision, query
  fields)`` holding check verdicts (positive AND negative) and lookup
  masks. Invalidation is free: every write bumps ``store.revision``, so
  stale keys simply stop being probed and age out of the LRU.
- **Expiration exactness**: revision bumps do not cover relationship
  *expiration* (the clock revokes grants without a write), so every entry
  carries a deadline — the store's next upcoming expiration boundary at
  fill time (:meth:`~.store.Store.next_expiry`). An entry is valid only
  while ``now < deadline``; explicit-``now`` queries bypass the cache
  entirely (engine.py routes them around this module).
- **Singleflight**: concurrent misses on the same key share ONE in-flight
  engine future instead of dispatching twice. Piggybacked callers block
  on the winner's :class:`Flight`; errors propagate to every waiter and
  are NOT cached. Joining an in-flight computation shares the winner's
  dispatch-time clock — exactly the semantics of a fused
  :class:`~.batcher.LookupBatcher` batch, which this layer sits in front
  of (the batcher only ever sees true misses).

Values are stored raw; the ENGINE copies masks on read so callers can
never mutate a cached array (copy-on-read). Metrics:
``engine_decision_cache_hits_total`` / ``_misses_total`` (labeled by
kind), ``_evictions_total``, ``_piggybacks_total``, and gauges
``engine_decision_cache_entries`` / ``_mask_bytes``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from ..utils.metrics import metrics

#: sentinel distinguishing "no entry" from any cached value (False/None
#: are legitimate verdicts — negative checks are cached too)
MISS = object()


class Flight:
    """One in-flight computation for a cache key — the singleflight unit.

    The leader registers the flight, dispatches the underlying engine
    future, then :meth:`launch`\\ es a ``finish`` thunk (result + cache
    fill). Followers (and the leader itself) call :meth:`result`, which
    runs ``finish`` exactly once and memoizes; errors re-raise to every
    caller and are never cached."""

    __slots__ = ("_lock", "_ready", "_finish", "_done", "_value", "_error",
                 "deadline")

    def __init__(self):
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._finish = None
        self._done = False
        self._value = None
        self._error: Optional[BaseException] = None
        # set by the leader's finish; lets a late joiner detect that the
        # resolved value's expiration deadline has already passed
        self.deadline = float("inf")

    def launch(self, finish) -> None:
        self._finish = finish
        self._ready.set()

    def abort(self, err: BaseException) -> None:
        """The leader's dispatch itself failed before a future existed:
        fail every waiter instead of leaving them parked forever."""
        with self._lock:
            self._error = err
            self._done = True
        self._ready.set()

    @property
    def done(self) -> bool:
        return self._done

    def result(self):
        self._ready.wait()
        with self._lock:
            if not self._done:
                try:
                    self._value = self._finish()
                except BaseException as e:  # noqa: BLE001 - fan out
                    self._error = e
                self._done = True
        if self._error is not None:
            raise self._error
        return self._value


class _Shard:
    __slots__ = ("lock", "entries", "mask_bytes")

    def __init__(self):
        self.lock = threading.Lock()
        # key -> (value, deadline, nbytes); insertion order IS recency
        self.entries: OrderedDict = OrderedDict()
        self.mask_bytes = 0


class DecisionCache:
    """Sharded-lock LRU + singleflight registry. Thread-safe.

    Budgets are split evenly across shards: ``max_entries`` bounds entry
    count (check verdicts and lookup masks alike) and ``max_mask_bytes``
    bounds resident mask payload bytes; whichever trips first evicts from
    that shard's cold end."""

    def __init__(self, max_entries: int = 65536,
                 max_mask_bytes: int = 256 << 20, shards: int = 16):
        shards = max(1, int(shards))
        self.max_entries = max(1, int(max_entries))
        self.max_mask_bytes = max(0, int(max_mask_bytes))
        self._shards = [_Shard() for _ in range(shards)]
        self._entry_budget = max(1, self.max_entries // shards)
        self._byte_budget = self.max_mask_bytes / shards
        self._flights: dict = {}
        self._flights_lock = threading.Lock()
        # set by clear(): an in-flight fill racing disable_decision_cache
        # must not re-populate (and re-inc the gauges of) a cache nothing
        # will ever clear again
        self._closed = False

    # -- LRU -----------------------------------------------------------------

    def _shard(self, key) -> _Shard:
        return self._shards[hash(key) % len(self._shards)]

    def get(self, key: tuple, now: float, record: bool = True):
        """The cached value for ``key`` (may be False/None), or
        :data:`MISS`. A valid hit refreshes recency; an entry whose
        deadline has passed is dropped on the spot. ``record=False``
        probes without touching hit/miss counters (the middleware
        fast-path probe, whose misses are re-counted by the real call)."""
        sh = self._shard(key)
        with sh.lock:
            ent = sh.entries.get(key)
            if ent is not None:
                if now < ent[1]:
                    sh.entries.move_to_end(key)
                    if record:
                        metrics.counter("engine_decision_cache_hits_total",
                                        kind=key[0]).inc()
                    return ent[0]
                # expired at the watermark: exact expiration semantics —
                # the entry dies the instant the boundary passes
                del sh.entries[key]
                sh.mask_bytes -= ent[2]
                metrics.gauge("engine_decision_cache_entries").dec()
                metrics.gauge("engine_decision_cache_mask_bytes").dec(ent[2])
        if record:
            metrics.counter("engine_decision_cache_misses_total",
                            kind=key[0]).inc()
        return MISS

    def note_hits(self, kind: str, n: int) -> None:
        """Credit ``n`` hits counted outside :meth:`get` (the record-less
        probe path, once it is known the whole probe was served)."""
        if n:
            metrics.counter("engine_decision_cache_hits_total",
                            kind=kind).inc(n)

    def put(self, key: tuple, value, deadline: float, nbytes: int,
            now: float) -> None:
        """Insert/refresh an entry. Born-dead entries (deadline already
        passed — a tuple expired while the query was in flight) are not
        stored."""
        if deadline <= now:
            return
        nbytes = int(nbytes)
        sh = self._shard(key)
        evicted = 0
        freed = 0
        added = 0
        with sh.lock:
            # re-checked under the shard lock: clear() sets the flag
            # BEFORE draining shards, so a fill can never land in a shard
            # clear() has already passed
            if self._closed:
                return
            old = sh.entries.pop(key, None)
            if old is not None:
                sh.mask_bytes -= old[2]
                freed += old[2]
                added -= 1
            sh.entries[key] = (value, deadline, nbytes)
            sh.mask_bytes += nbytes
            freed -= nbytes
            added += 1
            while len(sh.entries) > 1 and (
                    len(sh.entries) > self._entry_budget
                    or sh.mask_bytes > self._byte_budget):
                _, (_, _, nb) = sh.entries.popitem(last=False)
                sh.mask_bytes -= nb
                freed += nb
                evicted += 1
        if evicted:
            metrics.counter("engine_decision_cache_evictions_total").inc(
                evicted)
        metrics.gauge("engine_decision_cache_entries").inc(added - evicted)
        metrics.gauge("engine_decision_cache_mask_bytes").dec(freed)

    def clear(self) -> None:
        """Drop every entry (and fix the gauges) and refuse future fills:
        called when the engine disables the cache so /metrics does not
        report phantom residency — including from a fill that was already
        in flight when the cache was detached."""
        self._closed = True
        dropped = 0
        freed = 0
        for sh in self._shards:
            with sh.lock:
                dropped += len(sh.entries)
                freed += sh.mask_bytes
                sh.entries.clear()
                sh.mask_bytes = 0
        metrics.gauge("engine_decision_cache_entries").dec(dropped)
        metrics.gauge("engine_decision_cache_mask_bytes").dec(freed)

    def retire_below(self, revision: int) -> int:
        """Drop every entry keyed at a revision below ``revision``.

        Keys embed the store revision (``key[1]``), so entries of
        superseded revisions can never be probed again — under sustained
        write churn they would otherwise squat in the LRU until budget
        eviction, displacing live entries. Probing is revision-exact, so
        this sweep can never change an answer; the background compactor
        runs it at fold cadence (compaction.py) — amortized, never on
        the serving path. Entries AT ``revision`` survive: a compaction
        swap preserves the revision, so their keys stay exactly valid
        across it. Returns the number of entries dropped."""
        revision = int(revision)
        dropped = 0
        freed = 0
        for sh in self._shards:
            with sh.lock:
                dead = [k for k in sh.entries if k[1] < revision]
                for k in dead:
                    _, _, nb = sh.entries.pop(k)
                    sh.mask_bytes -= nb
                    freed += nb
                dropped += len(dead)
        if dropped:
            metrics.counter("engine_decision_cache_retired_total").inc(
                dropped)
            metrics.gauge("engine_decision_cache_entries").dec(dropped)
            metrics.gauge("engine_decision_cache_mask_bytes").dec(freed)
        return dropped

    def retire_affected(self, affected) -> int:
        """Drop only the entries whose query lies inside a schema diff's
        ``affected`` set of ``(resource_type, permission-or-relation)``
        pairs — the migration cutover's surgical alternative to a full
        flush. A check key carries the resource type at ``key[2]`` and
        the permission at ``key[4]``; a lookup key carries them at
        ``key[2]``/``key[3]``. Everything outside the set keeps its
        verdicts: the cutover swap preserves the store revision, so
        surviving keys stay exactly probe-valid — and the no-verdict-flap
        invariant depends on them answering identically across the flip.
        Returns the number of entries dropped."""
        affected = frozenset(affected)
        if not affected:
            return 0
        dropped = 0
        freed = 0
        for sh in self._shards:
            with sh.lock:
                dead = []
                for k in sh.entries:
                    pair = ((k[2], k[4]) if k[0] == "check"
                            else (k[2], k[3]))
                    if pair in affected:
                        dead.append(k)
                for k in dead:
                    _, _, nb = sh.entries.pop(k)
                    sh.mask_bytes -= nb
                    freed += nb
                dropped += len(dead)
        if dropped:
            metrics.counter("engine_decision_cache_retired_total").inc(
                dropped)
            metrics.gauge("engine_decision_cache_entries").dec(dropped)
            metrics.gauge("engine_decision_cache_mask_bytes").dec(freed)
        return dropped

    def stats(self) -> dict:
        with_entries = sum(len(sh.entries) for sh in self._shards)
        return {
            "entries": with_entries,
            "mask_bytes": sum(sh.mask_bytes for sh in self._shards),
        }

    # -- singleflight --------------------------------------------------------

    def flight(self, key: tuple, now: float) -> tuple[bool, Flight]:
        """Join or create the in-flight computation for ``key``. Returns
        ``(is_leader, flight)``; a follower's join is counted as a
        piggyback (one saved dispatch). A lingering resolved flight whose
        deadline has passed is replaced, never served stale."""
        with self._flights_lock:
            f = self._flights.get(key)
            if f is not None and f.done and now >= f.deadline:
                del self._flights[key]
                f = None
            if f is not None:
                metrics.counter(
                    "engine_decision_cache_piggybacks_total").inc()
                return False, f
            f = Flight()
            self._flights[key] = f
            return True, f

    def release(self, key: tuple, flight: Flight) -> None:
        """Retire ``flight`` from the registry (after the cache fill, so
        a racing prober lands on the cache entry, not a dead flight)."""
        with self._flights_lock:
            if self._flights.get(key) is flight:
                del self._flights[key]


def check_key(revision: int, item,
              ctx_digest: Optional[str] = None) -> tuple:
    """``ctx_digest`` (engine.context_digest) joins the key for
    caveat-contexted queries so a conditional verdict can never leak
    across request contexts; context-free queries keep the historical
    key shape unchanged."""
    base = ("check", revision, item.resource_type, item.resource_id,
            item.permission, item.subject_type, item.subject_id,
            item.subject_relation)
    return base if ctx_digest is None else base + (ctx_digest,)


def lookup_key(revision: int, resource_type: str, permission: str,
               subject_type: str, subject_id: str,
               subject_relation: Optional[str],
               ctx_digest: Optional[str] = None) -> tuple:
    base = ("lookup", revision, resource_type, permission, subject_type,
            subject_id, subject_relation)
    return base if ctx_digest is None else base + (ctx_digest,)
