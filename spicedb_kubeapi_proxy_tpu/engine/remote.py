"""Remote engine endpoint: the TPU engine served over TCP.

The reference proxy can point at a remote SpiceDB (`--spicedb-endpoint
host:port` with bearer token, /root/reference/pkg/proxy/options.go:325-369)
instead of the embedded one. This module is that deployment shape for the
TPU engine: one engine host owns the chip and N proxy replicas consume the
same engine API remotely — ``EngineServer`` wraps an in-process
:class:`Engine`; ``RemoteEngine`` is a drop-in client exposing the exact
surface the proxy consumes (check_bulk, lookup_resources,
write/read/delete relationships, watch_since, revision, store.exists).

Protocol: 4-byte big-endian length-prefixed frames.
    request:  JSON {"op": str, "token": str?, ...args}
    response: JSON {"ok": true, "result": ...}
            | JSON {"ok": false, "kind": str, "error": str}
            | binary: 0x00 byte + 4-byte meta length + meta JSON + payload
Errors round-trip by kind so precondition failures and schema violations
keep their meaning across the wire (the dual-write activities branch on
them). Transport security mirrors the reference's remote endpoint
(TLS with CA verification plus bearer token, options.go:325-369): the
host serves TLS from a cert/key pair (``--tls-cert-file``/``--tls-key-
file``, optional ``--tls-client-ca-file`` for mutual TLS) and refuses to
serve plaintext unless explicitly ``--engine-insecure``; clients verify
against the system store or ``--engine-ca-file`` (utils/tlsconf.py).

The binary response form exists for the list-filter hot path: the
``lookup_mask`` op returns the allowed set as a PACKED BITMASK over the
resource type's interned object space (1 bit per padded object index:
~16 KB at a bucket-padded 100k-object space) instead
of a multi-MB JSON id list, mirroring how the reference streams
LookupResources over gRPC rather than materializing strings
(/root/reference/pkg/authz/lookups.go:74). Mask indices resolve through a
client-side id table synced INCREMENTALLY via ``object_ids`` (interners
are append-only within a store epoch; a snapshot restore mints a new
epoch and invalidates client caches).
"""

from __future__ import annotations

import asyncio
import hmac
import json
import logging
import socket
import ssl
import struct
import threading
import time
from dataclasses import asdict
from functools import partial
from typing import Optional

from ..admission import AdmissionRejected, classify_op
from ..obs.trace import tracer
from ..utils.failpoints import FailPointError, failpoints
from ..utils.metrics import metrics
from ..utils.net import drain_server
from ..utils.resilience import (
    CircuitBreaker,
    Deadline,
    DependencyUnavailable,
    RetryBudget,
    RetryPolicy,
)

from ..models.schema import SchemaError
from ..models.tuples import Relationship
from .engine import CheckItem, Engine, SchemaViolation, WatchEvent
from .store import (
    Precondition,
    PreconditionFailed,
    RelationshipFilter,
    StoreError,
    WriteOp,
)

log = logging.getLogger("sdbkp.engine.remote")

MAX_FRAME = 256 * 1024 * 1024
# Until a connection has authenticated once, frames are capped far smaller:
# an auth frame is a few hundred bytes, and the big limit exists for bulk
# relationship payloads that only authenticated peers may send. Without this
# an unauthenticated socket could make the server buffer 256MiB per frame.
MAX_FRAME_PREAUTH = 1024 * 1024

class _EngineView:
    """The ONE attribute the ``_op_*`` handlers touch, pinned at
    role-gate time: handlers run as plain functions against this view,
    so a failover demotion swapping ``server.engine`` mid-request can
    never reroute an op onto the deposed leader's bare engine."""

    __slots__ = ("engine",)

    def __init__(self, engine):
        self.engine = engine


class _Demoted(Exception):
    """Server-internal: the role gate re-check at op-execution time found
    the host demoted after the event-loop gate passed (EngineServer.
    _dispatch maps it to the ``not_leader`` wire kind)."""

    def __init__(self, role, term):
        super().__init__(f"demoted to {role} (term {term})")
        self.role = role
        self.term = term


class NotLeaderError(DependencyUnavailable):
    """The engine host answered but is not the replication leader
    (role-gated follower, or a deposed leader mid-demotion). Subclasses
    :class:`~..utils.resilience.DependencyUnavailable` so the authz
    middleware fails it CLOSED as a retryable kube 503 + Retry-After;
    the failover client treats it as a re-resolve trigger — the op was
    rejected BEFORE dispatch, so even a write is safe to re-aim."""

    def __init__(self, message: str = ""):
        super().__init__(
            "engine-leader",
            message or "engine host is not the replication leader",
            retry_after=1.0)


class RemoteEngineError(RuntimeError):
    pass


class EngineInternalError(RemoteEngineError):
    """The engine host ANSWERED kind="internal": an exception inside its
    op handler (including chaos-armed server-side faults). Distinct from
    the RemoteEngineError base — which also covers auth/proto/frame
    errors that are PERMANENT (wrong token, oversized frame) — so the
    authz middleware can map only genuine host-side failures to the
    retryable fail-closed 503 family without turning a misconfiguration
    into an endlessly-retried "transient" outage."""


_ERROR_KINDS = {
    "precondition": PreconditionFailed,
    "schema": SchemaViolation,
    "store": StoreError,
    "not_leader": NotLeaderError,
    "internal": EngineInternalError,
}

# ops that are safe to retry after a transport failure even if the
# request bytes reached the engine host: pure reads. Writes
# (write/delete_relationships) are NEVER in this set — once bytes are on
# the wire the server may have applied them, and a replay would
# double-apply (the no-retry-after-send invariant in _transact).
_IDEMPOTENT_OPS = frozenset({
    "check_bulk", "lookup_resources", "lookup_mask", "lookup_subjects",
    "object_ids", "revision", "exists", "watch_since", "watch_gate",
    "read_relationships", "traces",
    # the rebalance mover's slice ops are idempotent BY CONSTRUCTION
    # (slice_read is a pure read; slice_load/slice_apply replay as
    # TOUCH/last-per-key effects; slice_drop deletes are idempotent),
    # so unlike ordinary writes they are safe to re-send after an
    # ambiguous transport death — exactly what a mid-copy SIGKILL of a
    # group leader produces
    "slice_read", "slice_load", "slice_apply", "slice_drop",
    "slice_watch",
    # migration control reads + level-triggered controls: status is a
    # pure read; cut/abort converge to the same terminal state however
    # many times they land. migrate_begin is NOT here — a replay would
    # race the single-active-migration refusal.
    "migrate_status", "migrate_cut", "migrate_abort",
    # frontier exchange: both legs are pure reads (pair derivation is
    # a schema walk; expansion is a batch of lookup_resources)
    "frontier_expand", "frontier_pairs",
    # autoscaler signal probe: a pure read of admission/latency state
    "load_status",
})

# "the transport failed" (vs the engine answering with an error): socket
# errors — connect refused/reset/timeout, TLS failures — plus armed
# failpoints so chaos tests drive the same classification
TRANSPORT_ERRORS = (OSError, FailPointError)

# ops exempt from the server-side fault sites (engine.dispatch /
# engine.respond): the chaos CONTROL plane and failover resolution. A
# p=1 error/drop schedule would otherwise brick its own chaos_reset —
# an unrecoverable host where the campaign meant a recoverable fault —
# and blind the client-side leader discovery the campaign steers by.
_CHAOS_EXEMPT_OPS = frozenset({
    "chaos_arm", "chaos_reset", "chaos_status", "failover_state",
})


# -- codecs ------------------------------------------------------------------


def _rel_to_dict(r: Relationship) -> dict:
    return asdict(r)


def _rel_from_dict(d: dict) -> Relationship:
    return Relationship(**d)


def _filter_from_dict(d: dict) -> RelationshipFilter:
    return RelationshipFilter(**d)


def po2_chunks(n: int, cap: int = 2048):
    """Split ``n`` rows into descending power-of-two chunk sizes
    (capped): the overlay's device scatter specializes per CHUNK SHAPE,
    so arbitrary mover batch sizes would each pay an XLA compile while
    holding the engine write path — with po2 bucketing at most
    ``log2(cap)`` shapes ever exist, compiled once and reused across
    every slice, round, and transition."""
    sizes = []
    c = 1
    while c < cap:
        c <<= 1
    while n > 0:
        while c > n:
            c >>= 1
        sizes.append(c)
        n -= c
    return sizes


def _apply_po2(engine, rows, op: "str | None") -> int:
    """Apply mover rows through the ordinary write path in power-of-two
    chunks (see :func:`po2_chunks` — shape-stable overlay scatters, no
    per-batch-size XLA compile on the write lock). ``op`` of None means
    ``rows`` are WriteOps already. Module-level on purpose: op handlers
    run against the role-gate's slim ``_EngineView`` pin, not the
    server object."""
    rev = engine.revision
    i = 0
    for c in po2_chunks(len(rows)):
        chunk = rows[i:i + c]
        rev = engine.write_relationships(
            chunk if op is None else [WriteOp(op, r) for r in chunk])
        i += c
    return rev


def _watch_events_wire(engine, revision) -> list:
    """watch_since -> wire form (shared by the tenant watch op and the
    mover's rebalance-classed twin; module-level because op handlers
    run against the role-gate's slim ``_EngineView`` pin)."""
    return [
        {"revision": e.revision, "operation": e.operation,
         "rel": _rel_to_dict(e.relationship)}
        for e in engine.watch_since(revision)
    ]


def _slice_rows(engine, ranges, want_globals: bool) -> list:
    """Live relationships in the requested partition-key hash ranges
    (or the replicated global tuples) — the slice_read/slice_drop row
    scan, shared with the in-process fallback in scaleout/rebalance."""
    # function-level import: scaleout imports this module at load time
    from ..scaleout.shardmap import hash_key, split_resource

    rows = []
    for rel in engine.read_relationships(RelationshipFilter()):
        ns, namespaced = split_resource(rel.resource_id)
        if want_globals:
            if not namespaced:
                rows.append(rel)
            continue
        if not namespaced:
            continue
        h = hash_key(ns, rel.resource_type)
        if any(lo <= h < hi for lo, hi in ranges):
            rows.append(rel)
    return rows


def _rels_to_cols(rels: list) -> dict:
    """Relationship rows -> the columnar bulk form the PR 3 npz codec
    carries (None expirations become NaN; optional strings become
    empty — ``_cols_to_rels`` is the inverse)."""
    cols = {k: [] for k in (
        "resource_type", "resource_id", "relation", "subject_type",
        "subject_id", "subject_relation", "expiration", "caveat",
        "caveat_context")}
    for r in rels:
        cols["resource_type"].append(r.resource_type)
        cols["resource_id"].append(r.resource_id)
        cols["relation"].append(r.relation)
        cols["subject_type"].append(r.subject_type)
        cols["subject_id"].append(r.subject_id)
        cols["subject_relation"].append(r.subject_relation or "")
        cols["expiration"].append(r.expiration)
        cols["caveat"].append(r.caveat or "")
        cols["caveat_context"].append(r.caveat_context or "")
    return cols


def _cols_to_rels(cols: dict) -> list:
    import math

    n = len(cols.get("resource_id", ()))
    srl = cols.get("subject_relation")
    exp = cols.get("expiration")
    cav = cols.get("caveat")
    ctx = cols.get("caveat_context")

    def opt(col, i):
        if col is None:
            return None
        v = str(col[i])
        return v or None

    out = []
    for i in range(n):
        e = None
        if exp is not None:
            ev = float(exp[i])
            e = None if (math.isnan(ev) or math.isinf(ev)) else ev
        out.append(Relationship(
            str(cols["resource_type"][i]), str(cols["resource_id"][i]),
            str(cols["relation"][i]), str(cols["subject_type"][i]),
            str(cols["subject_id"][i]), opt(srl, i), e,
            opt(cav, i), opt(ctx, i)))
    return out


# -- framing -----------------------------------------------------------------


def _pack(msg: dict) -> bytes:
    body = json.dumps(msg).encode()
    return struct.pack(">I", len(body)) + body


class BinaryResult:
    """An op result carried as a binary frame (meta JSON + raw payload)
    instead of the normal ``{"ok": true, "result": ...}`` JSON."""

    __slots__ = ("meta", "payload")

    def __init__(self, meta: dict, payload: bytes):
        self.meta = meta
        self.payload = payload


def _pack_binary(res: BinaryResult) -> bytes:
    # a leading NUL distinguishes binary frames: JSON bodies always start
    # with '{'
    meta = json.dumps(res.meta).encode()
    body = b"\x00" + struct.pack(">I", len(meta)) + meta + res.payload
    return struct.pack(">I", len(body)) + body


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionResetError("engine connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame_sync(s: socket.socket):
    """Blocking read of one response frame off a socket: a parsed JSON
    dict, or ``(meta, payload)`` for binary frames. The ONE place client-
    side framing lives (request path and watch push stream both use it)."""
    header = _recv_exact(s, 4)
    (n,) = struct.unpack(">I", header)
    if n > MAX_FRAME:
        raise RemoteEngineError(f"frame of {n} bytes exceeds limit")
    body = _recv_exact(s, n)
    if body[:1] == b"\x00":
        (m,) = struct.unpack(">I", body[1:5])
        return json.loads(body[5:5 + m]), body[5 + m:]
    return json.loads(body)


async def _read_frame(reader: asyncio.StreamReader,
                      limit: int = MAX_FRAME) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = struct.unpack(">I", header)
    if n > limit:
        raise RemoteEngineError(f"frame of {n} bytes exceeds limit")
    body = await reader.readexactly(n)
    return json.loads(body)


# -- server ------------------------------------------------------------------


class EngineServer:
    """Serves an :class:`Engine` to remote proxies. Device queries run in
    worker threads so slow fixpoints never stall other connections'
    dispatches — concurrent queries pipeline on the device the same way
    in-process callers do.

    The workers come from a DEDICATED executor, not the loop's default
    pool: push-watch streams park a thread per subscriber waiting for
    events, and batched lookups (enable_lookup_batching) park up to
    max_rows threads per fill window — on a small host the default
    pool's min(32, cpus+4) workers would starve request handling (and an
    embedding application's own to_thread users would compete with the
    engine)."""

    def __init__(self, engine: Engine, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 ssl_context=None, max_workers: int = 64,
                 failover_status=None, admission=None,
                 allow_chaos: bool = False):
        from concurrent.futures import ThreadPoolExecutor

        self.engine = engine
        # test-only fault plane (--enable-chaos-ops): when on, the
        # chaos_arm/chaos_reset/chaos_status wire ops let a campaign
        # runner install seeded fault schedules into THIS process's
        # failpoint registry — the only way to drive deterministic
        # multi-process chaos against subprocess engine hosts. Off by
        # default and meant to stay off outside test topologies.
        self.allow_chaos = allow_chaos
        self.host = host
        self.port = port
        self.token = token
        # admission controller (admission/): device-dispatching ops
        # acquire a cost-classed slot — tenant = the proxy replica's peer
        # address — BEFORE entering the worker pool, so one replica's
        # storm cannot monopolize a shared engine host and overload sheds
        # as wire-level "admission" rejections instead of queueing
        # unboundedly in the executor. None = ungated (today's behavior).
        self.admission = admission
        # replication role provider (parallel/failover.py coordinator):
        # a callable returning {role, term, revision, peer_id, lag}.
        # When set, every op except failover_state is ROLE-GATED — a
        # follower (or electing) host rejects with kind "not_leader"
        # instead of answering from possibly-stale state. None = the
        # single-host default: this process IS the leader of itself.
        self.failover_status = failover_status
        # heartbeat cadence on idle mirror streams; failover deployments
        # shrink it so followers detect a dead leader in seconds, not
        # PUSH_HEARTBEAT multiples
        self.mirror_heartbeat = self.PUSH_HEARTBEAT
        # an ssl.SSLContext makes every connection TLS (utils/tlsconf.py:
        # the reference's remote endpoint is TLS-by-default,
        # options.go:325-369); None serves plaintext — the standalone CLI
        # refuses that combination unless --engine-insecure is explicit
        self.ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()  # live connection-handler tasks
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="engine-host")

    async def _in_worker(self, fn, *args):
        """Run blocking work on the dedicated pool (to_thread semantics,
        minus contextvars, which the handlers don't use)."""
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, partial(fn, *args))

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("engine listening on %s:%d%s", self.host, self.port,
                 " (TLS)" if self.ssl_context else "")
        return self.port

    async def stop(self, grace: float = 2.0) -> None:
        """Stop listening and drain connections (utils/net.py: clients
        pool idle sockets blocked in _read_frame, which ``wait_closed()``
        would wait on forever on Python 3.12+)."""
        if self._server is None:
            return
        store = getattr(self.engine, "store", None)
        waker = None
        if hasattr(store, "wake_waiters"):
            # repeatedly release push loops parked in wait_events during
            # the drain (a cancelled to_thread only unblocks when the
            # worker thread returns; a single wake can race a loop that
            # re-parks before its cancellation lands) — without this,
            # each active watch_subscribe stream holds the drain for up
            # to PUSH_HEARTBEAT seconds
            async def _wake_loop():
                while True:
                    store.wake_waiters()
                    await asyncio.sleep(0.2)

            waker = asyncio.get_running_loop().create_task(_wake_loop())
        try:
            await drain_server(self._server, self._conns, grace)
        finally:
            if waker is not None:
                waker.cancel()
        # drained handlers have returned their workers; drop the pool
        # without joining stragglers (a parked wait_events unblocks at
        # its heartbeat timeout — the drain's waker already released the
        # common case)
        self._executor.shutdown(wait=False, cancel_futures=True)
        self._server = None

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await self._serve_inner(reader, writer)
        finally:
            self._conns.discard(task)

    async def _serve_inner(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        authed = not self.token
        # admission tenancy: the peer ADDRESS (one tenant per proxy
        # replica, however many pooled connections it opens) — server-
        # derived, never client-asserted, so a token holder cannot mint
        # fresh tenants to reset its fair-queue debt
        peer = writer.get_extra_info("peername")
        peer_tenant = peer[0] if isinstance(peer, (tuple, list)) and peer \
            else "local"
        try:
            while True:
                limit = MAX_FRAME if authed else MAX_FRAME_PREAUTH
                req = await _read_frame(reader, limit=limit)
                if req is None:
                    return
                resp = await self._dispatch(req, peer_tenant)
                if req.get("op") not in _CHAOS_EXEMPT_OPS \
                        and failpoints.branch("engine.respond"):
                    # chaos: the response falls into the void — the
                    # client sees a reset (its request MAY have applied:
                    # exactly the ambiguity the no-retry-after-send
                    # write rule and the split-journal pending rule are
                    # specified against)
                    return
                if isinstance(resp, BinaryResult):
                    authed = True
                    writer.write(_pack_binary(resp))
                else:
                    if resp.get("ok") or resp.get("kind") != "auth":
                        authed = True
                    writer.write(_pack(resp))
                await writer.drain()
                if not isinstance(resp, BinaryResult) and resp.get("ok") \
                        and req.get("op") == "watch_subscribe":
                    # the ack is out; the connection now becomes a
                    # one-way server-push event stream
                    await self._push_events(writer,
                                            int(req["from_revision"]))
                    return
                if not isinstance(resp, BinaryResult) and resp.get("ok") \
                        and req.get("op") == "mirror_subscribe":
                    # multi-host follower: stream every mirrored engine
                    # action (parallel/multihost.py MirroredEngine);
                    # the reader now carries only follower acks
                    await self._push_mirror(reader, writer, req)
                    return
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("engine connection error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _dispatch(self, req: dict, tenant: str = "local") -> dict:
        if self.token and not hmac.compare_digest(
                str(req.get("token") or ""), self.token):
            return {"ok": False, "kind": "auth", "error": "invalid token"}
        op = req.get("op")
        # trace stitching: the proxy forwards its span context as the
        # "tr" frame field (a W3C traceparent); engine-host spans (queue
        # wait, device dispatch, replication ack wait) attach under it —
        # into the SAME live trace when proxy and host share a process,
        # as a same-trace_id satellite fragment across processes
        with tracer.adopt(req.get("tr"), f"engine_host.{op}",
                          endpoint=f"{self.host}:{self.port}",
                          tenant=tenant):
            return await self._dispatch_traced(req, op, tenant)

    async def _dispatch_traced(self, req: dict, op, tenant: str) -> dict:
        ticket = None
        try:
            fn = getattr(self, f"_op_{op}", None)
            if fn is None:
                return {"ok": False, "kind": "proto",
                        "error": f"unknown op {op!r}"}
            if self.failover_status is not None \
                    and op not in ("failover_state", "traces",
                                   "chaos_arm", "chaos_reset",
                                   "chaos_status"):
                # chaos ops are control-plane like failover_state: a
                # campaign must be able to arm faults on FOLLOWERS (the
                # crash/partition targets) — a role gate would restrict
                # chaos to whichever host happens to lead
                # traces is diagnostics like failover_state: an operator
                # following a trace through a follower (or a deposed
                # leader) must be able to read its fragments
                st = self.failover_status()
                if st.get("role") != "leader":
                    # fail CLOSED, never stale: a follower's store trails
                    # the leader and a deposed leader's may be fenced off
                    return {"ok": False, "kind": "not_leader",
                            "error": f"engine host is {st.get('role')} "
                                     f"(term {st.get('term')}), not the "
                                     "replication leader"}
                # PIN the gate-approved engine for the op's whole
                # execution: ops dereference `self.engine` at call time,
                # so a demotion landing between this gate and the worker
                # slot would otherwise run a write against the freshly-
                # unwrapped BARE engine of a deposed leader (no mirror
                # frame, no term stamp, no replication floor). Running
                # the handler against an _EngineView closes that: even
                # if the op races a demotion, it goes through the term-
                # stamped mirrored wrapper — whose frames a newer
                # lineage fences and whose floored writes fail closed.
                view = _EngineView(self.engine)
                inner_fn = getattr(type(self), f"_op_{op}")

                def fn(r):  # noqa: F811 - deliberate gated shadow
                    st2 = self.failover_status()
                    if st2.get("role") != "leader":
                        # demotion already visible: reject rather than
                        # run a doomed (fenced) op to completion
                        raise _Demoted(st2.get("role"), st2.get("term"))
                    return inner_fn(view, r)
            if self.admission is not None:
                cls = classify_op(op, len(req.get("items") or ()) or 1)
                if cls is not None:
                    # admission runs AFTER the role gate (a follower's
                    # not_leader must win — its rejection re-aims the
                    # client) and BEFORE the worker pool: queued ops park
                    # a future here, not an executor thread. Tenancy is
                    # the PEER ADDRESS only — a wire-level override would
                    # let any token holder mint fresh zero-debt tenants
                    # per request and defeat the fair queue entirely
                    with tracer.span("engine_queue_wait",
                                     **{"class": cls.name}):
                        ticket = await self.admission.acquire_async(
                            tenant, cls)
            if op not in _CHAOS_EXEMPT_OPS \
                    and failpoints.armed("engine.dispatch"):
                # server-side fault site (chaos schedules): runs in the
                # WORKER thread so a delay action models a browned-out
                # device/host without stalling the event loop, an error
                # action a host answering with internal failures, and a
                # crash action a hard process death mid-dispatch
                inner0 = fn

                def fn(r, _inner=inner0):  # noqa: F811
                    failpoints.hit("engine.dispatch")
                    return _inner(r)
            captured = tracer.capture()
            if captured is not None:
                # run_in_executor does NOT copy contextvars: re-enter the
                # trace inside the worker so the device span (and any
                # replication-ack-wait span under it) stitches correctly
                inner = fn

                def fn(r, _inner=inner, _cap=captured):  # noqa: F811
                    with tracer.activate(_cap), \
                            tracer.span("engine_device", op=op):
                        return _inner(r)
            result = await self._in_worker(fn, req)
            if isinstance(result, BinaryResult):
                return result
            return {"ok": True, "result": result}
        except AdmissionRejected as e:
            # NOT a transport failure: rides a normal response frame, so
            # client breakers stay closed (the host is healthy, just full)
            return {"ok": False, "kind": "admission", "error": str(e),
                    "class": e.op_class, "retry_after": e.retry_after}
        except _Demoted as e:
            return {"ok": False, "kind": "not_leader",
                    "error": f"engine host was demoted to {e.role} "
                             f"(term {e.term}) before the op dispatched"}
        except PreconditionFailed as e:
            return {"ok": False, "kind": "precondition", "error": str(e)}
        except SchemaViolation as e:
            return {"ok": False, "kind": "schema", "error": str(e)}
        except SchemaError as e:
            # migrate_begin's typed incompatible refusal (and any parse
            # error in the proposed schema) is a SCHEMA answer, not a
            # host-side failure — kind "internal" would invite retries
            # against a permanent condition
            return {"ok": False, "kind": "schema", "error": str(e)}
        except StoreError as e:
            return {"ok": False, "kind": "store", "error": str(e)}
        except Exception as e:
            log.exception("engine op %s failed", op)
            return {"ok": False, "kind": "internal", "error": str(e)}
        finally:
            if ticket is not None:
                # the limiter's latency probe is the SINGLE-CHECK class
                # only — the one op whose duration is homogeneous.
                # Bulk-check spans scale with item count, lookups with
                # the fixpoint, and replicated writes with the sync-
                # replication wait: feeding that mixture to one baseline
                # would read op VARIETY as congestion and ratchet the
                # limit to minimum on a healthy host (device queueing
                # still surfaces in check latency — same chip). The
                # other classes still occupy weighted budget while held.
                ticket.release(
                    observe=ticket.cls.name == "check")

    # -- ops (run in worker threads) ----------------------------------------

    def _op_check_bulk(self, req: dict):
        items = [CheckItem(*it) for it in req["items"]]
        return self.engine.check_bulk(items, now=req.get("now"),
                                      context=req.get("ctx") or None)

    def _op_lookup_resources(self, req: dict):
        return self.engine.lookup_resources(
            req["resource_type"], req["permission"], req["subject_type"],
            req["subject_id"], req.get("subject_relation"),
            now=req.get("now"), context=req.get("ctx") or None)

    def _op_lookup_subjects(self, req: dict):
        return self.engine.lookup_subjects(
            req["resource_type"], req["resource_id"], req["permission"],
            req["subject_type"], req.get("subject_relation"),
            now=req.get("now"), context=req.get("ctx") or None)

    def _op_frontier_pairs(self, req: dict):
        """The schema's frontier reference pairs (scaleout/frontier.py)
        — raises the monotonicity refusal server-side so a planner
        enabling the exchange against an unsupported schema fails
        closed on first use."""
        from ..scaleout.frontier import reference_pairs

        return [list(p) for p in reference_pairs(self.engine.schema)]

    def _op_frontier_expand(self, req: dict):
        """One frontier-exchange leg against THIS group's local tuples
        (scaleout/frontier.py expand_local — one owner for the
        semantics, in-process and over the wire)."""
        from ..scaleout.frontier import decode_frontier, expand_local

        out = expand_local(
            self.engine, decode_frontier(req["descs"]),
            [(str(t), str(r)) for t, r in req["pairs"]],
            now=req.get("now"), context=req.get("ctx") or None)
        return sorted(([t, i, r] for t, i, r in out),
                      key=lambda d: (d[0], d[1], d[2] or ""))

    def _op_lookup_mask(self, req: dict):
        """The hot-path variant: packed bitmask over the type's object
        index space (see module docstring): constant-size, ~16 KB at a
        bucket-padded 100k-object space."""
        import numpy as np

        for _ in range(3):
            # bracket the query with epoch reads: a concurrent snapshot
            # restore between them would otherwise stamp OLD-interner mask
            # indices with the NEW epoch — exactly the aliasing the epoch
            # exists to prevent (the client would resolve wrong names)
            epoch = self.engine.store.epoch
            mask, interner = self.engine.lookup_resources_mask(
                req["resource_type"], req["permission"],
                req["subject_type"], req["subject_id"],
                req.get("subject_relation"), now=req.get("now"),
                context=req.get("ctx") or None)
            if self.engine.store.epoch != epoch:
                continue
            if mask is None:
                return {"found": False}
            return BinaryResult(
                {"found": True, "n": int(mask.size), "gen": len(interner),
                 "epoch": epoch},
                np.packbits(mask).tobytes())
        raise StoreError("store epoch kept changing during lookup")

    def _op_object_ids(self, req: dict):
        """Incremental id-table sync: strings interned at or past ``from``
        for a resource type. Append-only within an epoch, so clients fetch
        only the delta."""
        store = self.engine.store
        with store._lock:
            epoch = store.epoch
            tid = store.types.lookup(req["type"])
            it = store.objects.get(tid) if tid is not None else None
            if it is None:
                return {"epoch": epoch, "gen": 0, "ids": []}
            strings = it.strings()
        start = max(0, int(req.get("from", 0)))
        return {"epoch": epoch, "gen": len(strings),
                "ids": strings[start:]}

    def _op_write_relationships(self, req: dict):
        ops = [WriteOp(o["op"], _rel_from_dict(o["rel"]))
               for o in req["ops"]]
        pcs = [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
               for p in req.get("preconditions", [])]
        return self.engine.write_relationships(ops, pcs)

    def _op_delete_relationships(self, req: dict):
        pcs = [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
               for p in req.get("preconditions", [])]
        return self.engine.delete_relationships(
            _filter_from_dict(req["filter"]), pcs)

    def _op_read_relationships(self, req: dict):
        return [_rel_to_dict(r) for r in self.engine.read_relationships(
            _filter_from_dict(req["filter"]))]

    # seconds between keepalive frames on an idle push stream (lets the
    # client distinguish "no events" from a dead peer)
    PUSH_HEARTBEAT = 15.0

    def _op_watch_subscribe(self, req: dict):
        """Ack only — _serve_inner switches the connection into the push
        loop after this response is written (reference watches are a
        long-lived server-push stream, pkg/authz/watch.go:29)."""
        int(req["from_revision"])  # validate now, fail as a JSON error
        return {"subscribed": True, "revision": self.engine.revision}

    async def _push_events(self, writer: asyncio.StreamWriter,
                           from_rev: int) -> None:
        """Server-push loop: block on the store's revision condition (in a
        worker thread) and write each event batch as it lands — no
        client polling, grant/revoke latency = write latency + one
        one-way trip. Heartbeats mark liveness on idle streams."""
        rev = from_rev
        while True:
            try:
                events = await self._in_worker(
                    self.engine.wait_events, rev, self.PUSH_HEARTBEAT)
            except StoreError as e:
                writer.write(_pack({"ok": False, "push": True,
                                    "kind": "store", "error": str(e)}))
                await writer.drain()
                return
            if events:
                rev = max(e.revision for e in events)
            writer.write(_pack({
                "ok": True, "push": True, "revision": rev,
                "events": [
                    {"revision": e.revision, "operation": e.operation,
                     "rel": _rel_to_dict(e.relationship)}
                    for e in events
                ]}))
            await writer.drain()

    def _op_mirror_subscribe(self, req: dict):
        """Ack for a multi-host follower subscription; _serve_inner then
        switches the connection into the mirror-push loop. Only valid
        when the engine is a MirroredEngine leader. An optional
        ``from_revision`` (a restarting follower's recovered revision)
        makes the stream open with a catch-up frame — the delta from the
        leader's watch history, or a full state transfer when that
        history no longer reaches back far enough."""
        if not hasattr(self.engine, "subscribe"):
            raise StoreError(
                "engine host is not a multi-host leader "
                "(no MirroredEngine)")
        if "from_revision" in req:
            int(req["from_revision"])  # validate now, fail as a JSON error
            if not hasattr(self.engine, "subscribe_with_catchup"):
                raise StoreError(
                    "engine host does not support follower catch-up")
        return {"subscribed": True,
                "term": int(getattr(self.engine, "term", 0) or 0)}

    async def _mirror_ack_reader(self, reader: asyncio.StreamReader,
                                 q, eng) -> None:
        """Drain follower acknowledgements off the (otherwise one-way)
        mirror stream: ``{"ack": seq, "term": t}`` frames credit the
        subscriber's replication progress — the leader's sync-replicated
        writes wait on them (MirroredEngine._wait_replicated). ``eng``
        is the engine object PINNED by _push_mirror at subscribe time:
        acks belong to that wrapper's subscription, not to whatever a
        failover demotion may have swapped into self.engine since."""
        while True:
            frame = await _read_frame(reader)
            if frame is None:
                return
            seq = frame.get("ack")
            if seq is not None and hasattr(eng, "record_ack"):
                eng.record_ack(q, int(seq), frame.get("term"))

    async def _push_mirror(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           req: dict) -> None:
        import queue as _queue

        # pin the engine object: a failover demotion swaps self.engine
        # mid-stream, and the queue must be unsubscribed from the SAME
        # wrapper that registered it
        engine = self.engine
        if not hasattr(engine, "subscribe"):
            # demoted between the gated mirror_subscribe ack and this
            # push loop: the bare engine has no mirror surface — close
            # with the honest rejection, not an AttributeError
            writer.write(_pack({"ok": False, "kind": "not_leader",
                                "error": "engine host was demoted before "
                                         "the mirror stream started"}))
            await writer.drain()
            return
        if "from_revision" in req:
            # atomic cut (multihost.py subscribe_with_catchup): the
            # catch-up lands the follower at exactly the revision the
            # queued live frames continue from
            q, meta, payload = await self._in_worker(
                partial(engine.subscribe_with_catchup,
                        int(req["from_revision"]),
                        subscriber_term=req.get("term")))
        else:
            q, meta, payload = engine.subscribe(), None, None
        acks = asyncio.get_running_loop().create_task(
            self._mirror_ack_reader(reader, q, engine))
        try:
            if meta is not None:
                frame = {"ok": True, "catchup": meta}
                if payload is not None:
                    writer.write(_pack_binary(BinaryResult(frame, payload)))
                else:
                    writer.write(_pack(frame))
                await writer.drain()
            while True:
                try:
                    wire = await self._in_worker(
                        q.get, True, self.mirror_heartbeat)
                except _queue.Empty:
                    if failpoints.branch("mirror.heartbeat"):
                        continue  # chaos: suppressed liveness heartbeat
                    hb = {"ok": True, "hb": True}
                    term = int(getattr(engine, "term", 0) or 0)
                    if term:
                        hb["term"] = term
                    seq = getattr(engine, "mirror_seq", None)
                    if seq is not None:
                        hb["seq"] = int(seq)
                    writer.write(_pack(hb))
                    await writer.drain()
                    continue
                if wire is None:
                    # replication-timeout drop sentinel (MirroredEngine.
                    # _wait_replicated): close so the follower SEES it
                    return
                if failpoints.branch("mirror.partition"):
                    continue  # chaos: this frame falls into the void
                # pre-packed once by MirroredEngine._publish: the same
                # bytes object fans out to every follower
                writer.write(wire)
                await writer.drain()
        finally:
            acks.cancel()
            engine.unsubscribe(q)

    def _op_watch_since(self, req: dict):
        return _watch_events_wire(self.engine, req["revision"])

    def _op_slice_watch(self, req: dict):
        """watch_since for the rebalance mover's catch-up polls: the
        same answer, but admission-classed `rebalance` (lowest shed
        priority) — the mover's recurring polls must yield to tenant
        watch recomputes under saturation, per the migration-traffic
        contract."""
        return _watch_events_wire(self.engine, req["revision"])

    def _op_watch_gate(self, req: dict):
        types, use_exp = self.engine.watch_gate(
            req["resource_type"], req["name"])
        return {"types": sorted(types), "use_expiration": use_exp}

    def _op_revision(self, req: dict):
        return self.engine.revision

    def _op_failover_state(self, req: dict):
        """Replication-set introspection: NEVER role-gated — election
        probes and client-side failover resolution both depend on being
        able to ask a follower (or a deposed leader) what it is. A host
        with no coordinator is the leader of itself."""
        if self.failover_status is not None:
            return dict(self.failover_status())
        eng = self.engine
        return {"role": "leader",
                "term": int(getattr(eng, "term", 0) or 0),
                "revision": eng.revision, "peer_id": None, "lag": 0}

    def _op_load_status(self, req: dict):
        """Autoscaler signal probe (autoscale/controller.py): this
        host's admission occupancy (weighted in-flight cost over the
        AIMD limit) and mean engine check latency. Ungated
        control-plane like failover_state — a saturated host must
        still answer the probe that would relieve it."""
        occ = 0.0
        if self.admission is not None:
            st = self.admission.status()
            occ = max(0.0, min(1.0, float(st["inflight_cost"])
                               / max(1e-9, float(st["limit"]))))
        lat_ms = 0.0
        snap = metrics.hist_snapshot("engine_check_seconds")
        if snap and snap["n"]:
            lat_ms = snap["total"] / snap["n"] * 1e3
        return {"occupancy": occ, "check_ms": lat_ms}

    def _op_exists(self, req: dict):
        return self.engine.store.exists(_filter_from_dict(req["filter"]))

    # -- rebalance slice ops (scaleout/rebalance.py data plane) --------------
    # All idempotent, all admission-classed `rebalance` (lowest shed
    # priority): a live migration is cost-accounted and sheddable like
    # any tenant's bulk traffic.

    def _op_slice_read(self, req: dict):
        """Export the live namespaced tuples whose partition-key hash
        falls in the requested ``[lo, hi)`` ranges (or the replicated
        GLOBAL tuples with ``globals``), riding the npz codec as one
        binary frame. The revision is read BEFORE the row scan so the
        caller's catch-up replay covers any write that raced the scan
        (touch replays are idempotent: at-least-once)."""
        from ..persistence.codec import encode_bulk_cols

        ranges = [(int(lo), int(hi))
                  for lo, hi in (req.get("ranges") or ())]
        rev = int(self.engine.revision)
        rows = _slice_rows(self.engine, ranges,
                           bool(req.get("globals")))
        return BinaryResult({"slice": True, "revision": rev,
                             "n": len(rows)},
                            encode_bulk_cols(_rels_to_cols(rows)))

    def _op_slice_load(self, req: dict):
        """Idempotent slice import: the npz payload's rows apply as
        TOUCHes through the ordinary write path (validated, journaled,
        replicated, watch-logged — the merged sharded streams suppress
        these below the slice's cut revision)."""
        import base64

        from ..persistence.codec import decode_bulk_cols

        rels = _cols_to_rels(decode_bulk_cols(
            base64.b64decode(req["payload_b64"])))
        return {"revision": _apply_po2(self.engine, rels, "touch"),
                "rows": len(rels)}

    def _op_slice_apply(self, req: dict):
        """Catch-up replay: concrete touch/delete effects (already
        last-per-key deduped by the mover) through the ordinary write
        path."""
        ops = [WriteOp(o["op"], _rel_from_dict(o["rel"]))
               for o in req["ops"]]
        return {"revision": _apply_po2(self.engine, ops, None),
                "rows": len(ops)}

    def _op_slice_drop(self, req: dict):
        """GC after cutover: delete the moved rows — ordinary journaled
        deletes, idempotent, suppressed by the merged streams past the
        slice's cut revision."""
        ranges = [(int(lo), int(hi))
                  for lo, hi in (req.get("ranges") or ())]
        rows = _slice_rows(self.engine, ranges, False)
        return {"revision": _apply_po2(self.engine, rows, "delete"),
                "rows": len(rows)}

    def _op_traces(self, req: dict):
        """This host's recent kept-trace ring (diagnostics, never
        role-gated): cross-process deployments fetch their engine-side
        fragments through here — the proxy's /debug/traces merges them
        into its own traces by trace_id."""
        return tracer.recent(int(req.get("limit", 64)))

    # -- chaos control plane (flag-gated, test-only) -------------------------

    def _chaos_gate(self) -> None:
        if not self.allow_chaos:
            raise StoreError(
                "chaos ops are disabled on this host (boot with "
                "--enable-chaos-ops to accept fault schedules)")

    def _op_chaos_arm(self, req: dict):
        """Install a seeded fault schedule (chaos/schedule.py wire form)
        into this process's failpoint registry. Returns the schedule's
        digest so the campaign can pin that every process armed the
        byte-identical decision tables."""
        self._chaos_gate()
        from ..chaos.schedule import FaultSchedule

        sched = FaultSchedule.parse(req["schedule"])
        sched.arm()
        return {"armed": [s.site for s in sched.specs],
                "digest": sched.digest()}

    def _op_chaos_reset(self, req: dict):
        self._chaos_gate()
        failpoints.disable_all()
        return {"reset": True}

    def _op_chaos_status(self, req: dict):
        """Armed sites + trigger counts + this process's fault-history
        digest (deterministic for a given seed and request sequence)."""
        self._chaos_gate()
        return {"sites": failpoints.status(),
                "history": failpoints.history(),
                "history_digest": failpoints.history_digest()}

    # -- live schema migration control plane (migration/migrator.py) ---------
    # Admission-classed `rebalance` like the slice ops: a migration is
    # operator-driven bulk work, cost-accounted and sheddable beneath
    # tenant traffic. begin is NOT idempotent (a replay would race the
    # active-migration refusal); status/cut/abort are.

    def _op_migrate_begin(self, req: dict):
        """Start a live migration to the supplied schema text. The diff
        classification (and a typed incompatible refusal) happens on
        this call's stack — before any state change — so the caller gets
        the refusal reasons synchronously; the phase machine then runs
        in a background thread on this host."""
        kwargs = {}
        for k in ("batch", "parity_samples"):
            if req.get(k) is not None:
                kwargs[k] = int(req[k])
        if req.get("hold_at_dual") is not None:
            kwargs["hold_at_dual"] = bool(req["hold_at_dual"])
        if req.get("backfill_pause") is not None:
            kwargs["backfill_pause"] = float(req["backfill_pause"])
        return self.engine.begin_schema_migration(
            req["schema_text"], wait=bool(req.get("wait")), **kwargs)

    def _op_migrate_status(self, req: dict):
        return self.engine.migration_status()

    def _op_migrate_cut(self, req: dict):
        """Release a ``hold_at_dual`` migration into its cut; idempotent
        — re-requesting the cut of an already-cut (or done) migration
        just reports its status."""
        return self.engine.cut_schema_migration(
            wait=bool(req.get("wait", True)))

    def _op_migrate_abort(self, req: dict):
        return self.engine.abort_schema_migration()


# -- client ------------------------------------------------------------------


class RemoteWatchStream:
    """Client end of a server-push watch subscription: a DEDICATED socket
    (never pooled) on which the engine host pushes event batches.
    ``next_batch()`` blocks until a batch, heartbeat (``[]``), or error.
    Zero steady-state request traffic — the reference's long-lived gRPC
    watch stream shape (pkg/authz/watch.go:29)."""

    def __init__(self, client: "RemoteEngine", from_revision: int):
        self._s = client._connect()
        # heartbeats arrive every PUSH_HEARTBEAT; anything slower means a
        # dead peer, not an idle stream
        self._s.settimeout(EngineServer.PUSH_HEARTBEAT * 3 + 5.0)
        msg = {"op": "watch_subscribe", "from_revision": from_revision}
        if client.token:
            msg["token"] = client.token
        try:
            self._s.sendall(_pack(msg))
            ack = self._read()
        except Exception:
            self._s.close()
            raise
        if isinstance(ack, tuple) or not ack.get("ok"):
            self._s.close()
            kind = ack.get("kind", "internal") if isinstance(ack, dict) \
                else "proto"
            err = ack.get("error", "") if isinstance(ack, dict) else ""
            raise _ERROR_KINDS.get(kind, RemoteEngineError)(err)
        self.revision = ack["result"]["revision"]

    def _read(self):
        return _read_frame_sync(self._s)

    def next_batch(self) -> list:
        """Blocks for the next pushed frame; ``[]`` is a liveness
        heartbeat. Raises the mapped error kind when the server ends the
        stream (e.g. trimmed watch history -> StoreError)."""
        frame = self._read()
        if not frame.get("ok"):
            raise _ERROR_KINDS.get(frame.get("kind", "internal"),
                                   RemoteEngineError)(frame.get("error", ""))
        events = [
            WatchEvent(d["revision"], d["operation"],
                       _rel_from_dict(d["rel"]))
            for d in frame.get("events", [])
        ]
        if events:
            self.revision = max(e.revision for e in events)
        return events

    def close(self) -> None:
        try:
            self._s.close()
        except OSError:
            pass


class RemoteInterner:
    """Client-side id→string view over a synced table; the sliver of the
    Interner surface the lookup paths touch."""

    __slots__ = ("_strings",)

    def __init__(self, strings: list[str]):
        self._strings = strings

    def __len__(self) -> int:
        return len(self._strings)

    def string(self, i: int) -> str:
        return self._strings[i]


class _StoreShim:
    """The sliver of Store the proxy touches remotely (idempotency-key and
    lock existence probes)."""

    def __init__(self, client: "RemoteEngine"):
        self._client = client

    def exists(self, f: RelationshipFilter) -> bool:
        return self._client._call("exists", filter=asdict(f))


class RemoteEngine:
    """Synchronous client with the Engine surface the proxy consumes.
    Thread-safe: a small connection pool lets concurrent request handlers
    (asyncio.to_thread workers) issue queries in parallel."""

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 timeout: float = 300.0, connect_timeout: float = 10.0,
                 pool_size: int = 8, ssl_context=None,
                 server_hostname: Optional[str] = None,
                 retries: int = 2,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_seconds: float = 10.0,
                 retry_budget: Optional[RetryBudget] = None):
        self.host = host
        self.port = port
        self.token = token
        # dependency identity for breaker state, /readyz reasons, metrics
        self.dependency = f"engine:{host}:{port}"
        # retries apply ONLY to _IDEMPOTENT_OPS (reads); transport
        # failures on writes surface after exactly one attempt
        self.retries = retries
        self.retry_policy = retry_policy or RetryPolicy(base=0.05, cap=1.0)
        # shared token-bucket retry allowance (utils/resilience.py
        # RetryBudget): one budget spans the WHOLE client stack above a
        # dependency (this client, a FailoverEngine's re-aims, a
        # planner's scatter re-issues), so sustained failure can't
        # multiply retries across layers. None = unbudgeted.
        self.retry_budget = retry_budget
        self.breaker = breaker or CircuitBreaker(
            self.dependency,
            failure_threshold=breaker_failure_threshold,
            reset_timeout=breaker_reset_seconds)
        # TLS to the engine host (utils/tlsconf.client_ssl_context);
        # server_hostname overrides the SNI/verification name when the
        # dialed address is not the certificate's name (e.g. an IP)
        self.ssl_context = ssl_context
        self.server_hostname = server_hostname or host
        # response wait: generous — the first query after a snapshot
        # refresh pays an XLA compile measured in tens of seconds at the
        # 10M-relationship scale, and a timed-out-but-completing server op
        # would otherwise be retried against a still-busy server
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self._pool: list[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = pool_size
        self.store = _StoreShim(self)
        # per-type id tables synced from the engine host (append-only
        # within a store epoch): type -> (epoch, [strings])
        self._ids_lock = threading.Lock()
        self._ids: dict[str, tuple[str, list[str]]] = {}

    # -- transport ----------------------------------------------------------

    def _connect(self, deadline: Optional[Deadline] = None
                 ) -> socket.socket:
        failpoints.hit("engine.connect")
        connect_budget = self.connect_timeout
        read_budget = self.timeout
        if deadline is not None:
            connect_budget = deadline.budget(self.connect_timeout)
            read_budget = deadline.budget(self.timeout)
        s = socket.create_connection((self.host, self.port),
                                     timeout=connect_budget)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.ssl_context is not None:
            try:
                s = self.ssl_context.wrap_socket(
                    s, server_hostname=self.server_hostname)
            except Exception:
                s.close()
                raise
        s.settimeout(read_budget)
        return s

    def _acquire(self, deadline: Optional[Deadline] = None
                 ) -> tuple[socket.socket, bool]:
        """(live connection, fresh?): pooled sockets are liveness-probed
        first, so a stale one (engine host restarted, peer FIN pending) is
        replaced BEFORE any request bytes are written — retrying after a
        send could double-apply a write the server already processed.
        ``fresh`` tells the caller the server hasn't authenticated this
        connection yet (the pre-auth frame cap applies)."""
        while True:
            with self._pool_lock:
                if not self._pool:
                    break
                s = self._pool.pop()
            try:
                s.setblocking(False)
                try:
                    probe = s.recv(1)
                    alive = False  # b'' (FIN) or stray data: discard
                except (BlockingIOError, InterruptedError):
                    alive = True
                    probe = None
                except ssl.SSLWantReadError:
                    # TLS socket with no buffered record: alive (the
                    # plaintext path surfaces this case as BlockingIOError)
                    alive = True
                    probe = None
                if alive:
                    s.settimeout(self.timeout if deadline is None
                                 else deadline.budget(self.timeout))
                    return s, False
                del probe
            except OSError:
                pass
            s.close()
        return self._connect(deadline), True

    def _release(self, s: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(s)
                return
        s.close()

    def close(self) -> None:
        with self._pool_lock:
            for s in self._pool:
                s.close()
            self._pool.clear()

    def _call(self, op: str, **args):
        r = self._call_any(op, **args)
        if isinstance(r, tuple):
            raise RemoteEngineError(
                f"op {op!r} unexpectedly returned a binary frame")
        return r

    def _call_any(self, op: str, **args):
        """Like ``_call`` but passes binary responses through as a
        ``(meta, payload)`` tuple. Read ops retry transport failures
        (connect backoff included — a fresh connection is dialed per
        attempt once the pool is drained); every attempt is accounted to
        the endpoint's circuit breaker, and an open breaker fails fast
        with :class:`~..utils.resilience.BreakerOpen` before any
        connect."""
        msg = {"op": op, **args}
        if self.token:
            msg["token"] = self.token
        # span context rides the frame as a "tr" field (W3C traceparent)
        # so the engine host's spans stitch into this request's trace;
        # the rpc span brackets every attempt of this logical call —
        # under failover each endpoint tried appears as its own span
        rpc_span = tracer.begin("engine_rpc", op=op,
                                endpoint=self.dependency)
        if rpc_span is not None:
            msg["tr"] = rpc_span.traceparent()
        payload = _pack(msg)
        attempts = (self.retries + 1) if op in _IDEMPOTENT_OPS else 1
        delays = self.retry_policy.delays()
        if self.retry_budget is not None:
            self.retry_budget.on_attempt()
        # ONE wall-clock budget shared by every attempt: retries against
        # a host that accepts but never answers must not multiply the
        # caller's worst-case stall to attempts * read-timeout — the
        # self.timeout total is the bound either way (per-attempt socket
        # budgets are derived from what remains)
        deadline = Deadline.after(self.timeout)
        try:
            while True:
                attempts -= 1
                self.breaker.allow()
                start = time.monotonic()
                try:
                    resp = self._transact(payload, deadline)
                except TRANSPORT_ERRORS:
                    self.breaker.record_failure()
                    deadline.check(self.dependency)
                    if attempts <= 0:
                        raise
                    if self.retry_budget is not None \
                            and not self.retry_budget.allow():
                        # budget dry: surface the failure instead of
                        # joining a retry storm (the refusal is counted)
                        raise
                    metrics.counter("proxy_dependency_retries_total",
                                    dependency=self.dependency).inc()
                    time.sleep(min(next(delays), deadline.remaining()))
                    continue
                except BaseException:
                    # non-transport outcome (protocol/frame error,
                    # pre-auth rejection raised as an error kind): no
                    # verdict on the transport, but the admitted
                    # half-open probe slot must not leak or the breaker
                    # wedges open forever
                    self.breaker.release()
                    raise
                self.breaker.record_success()
                metrics.histogram("proxy_dependency_seconds",
                                  dependency=self.dependency).observe(
                    time.monotonic() - start)
                if isinstance(resp, tuple):
                    return resp  # (meta, payload) binary response
                if resp.get("ok"):
                    return resp.get("result")
                kind = resp.get("kind", "internal")
                err = resp.get("error", "")
                if kind == "admission":
                    # engine-host load shed: pre-dispatch by
                    # construction, so even writes are safe to retry
                    # after Retry-After. Its own dependency label keeps
                    # it distinguishable from proxy-side admission and
                    # from not_leader in the 503 metrics.
                    try:
                        retry_after = float(resp.get("retry_after") or 1.0)
                    except (TypeError, ValueError):
                        retry_after = 1.0
                    raise AdmissionRejected(
                        str(resp.get("class") or "?"), err,
                        retry_after=retry_after,
                        dependency="engine-admission")
                raise _ERROR_KINDS.get(kind, RemoteEngineError)(err)
        except BaseException as e:
            if rpc_span is not None:
                rpc_span.set("error", repr(e))
            raise
        finally:
            if rpc_span is not None:
                rpc_span.finish()

    def _transact(self, payload: bytes,
                  deadline: Optional[Deadline] = None):
        """ONE attempt: acquire a live connection, round-trip, release."""
        s, fresh = self._acquire(deadline)
        try:
            if fresh and self.token and len(payload) > MAX_FRAME_PREAUTH:
                # the server caps pre-auth frames; upgrade a fresh
                # connection with a cheap authenticated ping before the
                # big frame so bulk first-requests aren't dropped
                ping = self._round_trip(
                    s, _pack({"op": "revision", "token": self.token}))
                if not ping.get("ok"):
                    raise _ERROR_KINDS.get(
                        ping.get("kind", "internal"),
                        RemoteEngineError)(ping.get("error", ""))
            # no retry once bytes are on the wire for WRITES: the server
            # may have processed the op even if the connection then died,
            # and replaying a write would double-apply it (staleness is
            # handled by the pre-send liveness probe in _acquire). Reads
            # in _IDEMPOTENT_OPS retry at the _call_any layer.
            resp = self._round_trip(s, payload)
        except Exception:
            s.close()
            raise
        self._release(s)
        return resp

    def _round_trip(self, s: socket.socket, payload: bytes):
        s.sendall(payload)
        return self._read_response(s)

    def _read_response(self, s: socket.socket):
        """A JSON response dict, or (meta, payload) for binary frames."""
        failpoints.hit("engine.read")
        return _read_frame_sync(s)

    # -- engine surface ------------------------------------------------------

    def check(self, item: CheckItem, now: Optional[float] = None,
              context: Optional[dict] = None) -> bool:
        return self.check_bulk([item], now=now, context=context)[0]

    def check_bulk(self, items: list, now: Optional[float] = None,
                   context: Optional[dict] = None) -> list:
        # the request caveat context rides the frame as "ctx" (omitted
        # when empty so context-free frames stay byte-stable for older
        # hosts); the HOST's decision cache applies the context digest
        return self._call(
            "check_bulk", now=now, ctx=context or None,
            items=[[it.resource_type, it.resource_id, it.permission,
                    it.subject_type, it.subject_id, it.subject_relation]
                   for it in items])

    def lookup_subjects(self, resource_type: str, resource_id: str,
                        permission: str, subject_type: str,
                        subject_relation: Optional[str] = None,
                        now: Optional[float] = None,
                        context: Optional[dict] = None) -> list:
        return self._call(
            "lookup_subjects", resource_type=resource_type,
            resource_id=resource_id, permission=permission,
            subject_type=subject_type, subject_relation=subject_relation,
            now=now, ctx=context or None)

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None,
                         context: Optional[dict] = None) -> list:
        """Materialize allowed id strings from the mask wire (one ~16KB
        frame + an amortized id-table delta, not a multi-MB JSON list);
        falls back to the JSON op against hosts predating lookup_mask."""
        try:
            mask, interner = self.lookup_resources_mask(
                resource_type, permission, subject_type, subject_id,
                subject_relation, now=now, context=context)
        except RemoteEngineError:
            return self._call(
                "lookup_resources", resource_type=resource_type,
                permission=permission, subject_type=subject_type,
                subject_id=subject_id, subject_relation=subject_relation,
                now=now, ctx=context or None)
        from .engine import mask_to_ids

        return mask_to_ids(mask, interner)

    def load_status(self) -> dict:
        """The host's autoscaler signals: admission occupancy [0, 1]
        and mean engine check latency in ms."""
        return self._call("load_status")

    def frontier_pairs(self) -> tuple:
        """The group's schema-derived frontier reference pairs."""
        return tuple((str(t), str(r))
                     for t, r in self._call("frontier_pairs"))

    def frontier_expand(self, descs, pairs,
                        now: Optional[float] = None,
                        context: Optional[dict] = None) -> set:
        """One frontier-exchange leg on this group; descriptors cross
        the wire in the canonical encode_frontier form (the planner's
        wire-bytes counters measure exactly these payloads)."""
        got = self._call(
            "frontier_expand",
            descs=[[t, i, r] for t, i, r in descs],
            pairs=[[t, r] for t, r in pairs],
            now=now, ctx=context or None)
        return {(str(t), str(i), None if r is None else str(r))
                for t, i, r in got}

    def lookup_resources_mask(self, resource_type: str, permission: str,
                              subject_type: str, subject_id: str,
                              subject_relation: Optional[str] = None,
                              now: Optional[float] = None,
                              context: Optional[dict] = None):
        """(bool mask over the type's object index space, id view) — the
        same vectorized surface the in-process engine exposes
        (engine.py lookup_resources_mask), over the binary wire."""
        import numpy as np

        for _ in range(3):
            r = self._call_any(
                "lookup_mask", resource_type=resource_type,
                permission=permission, subject_type=subject_type,
                subject_id=subject_id, subject_relation=subject_relation,
                now=now, ctx=context or None)
            if not isinstance(r, tuple):
                return None, None  # {"found": False}
            meta, payload = r
            mask = np.unpackbits(
                np.frombuffer(payload, dtype=np.uint8),
                count=meta["n"]).astype(bool)
            interner = self._sync_ids(resource_type, meta["gen"],
                                      meta["epoch"])
            if interner is not None:
                return mask, interner
            # epoch changed between the mask and the id sync (snapshot
            # restore on the host): mask indices and table disagree —
            # retry the whole query against the new epoch
        raise RemoteEngineError(
            "engine host epoch kept changing during lookup")

    def _sync_ids(self, rtype: str, gen: int,
                  epoch: str) -> Optional[RemoteInterner]:
        """Bring the cached id table for ``rtype`` up to ``gen`` within
        ``epoch``; None when the host reports a DIFFERENT epoch (caller
        retries). Only the missing tail rides the wire, and the table is
        SHARED (append-only within an epoch) — no per-lookup copy of a
        100k-entry list on the hot path."""
        with self._ids_lock:
            ent = self._ids.get(rtype)
            if ent is None or ent[0] != epoch:
                ent = (epoch, [])
                self._ids[rtype] = ent
            strings = ent[1]
            have = len(strings)
        if have < gen:
            r = self._call("object_ids", type=rtype, **{"from": have})
            if r["epoch"] != epoch:
                with self._ids_lock:
                    # the delta we fetched belongs to ANOTHER epoch's
                    # table; drop the cache so the retry resyncs from 0
                    if self._ids.get(rtype) is ent:
                        self._ids.pop(rtype, None)
                return None
            with self._ids_lock:
                # a concurrent fetcher may have extended past us: append
                # only the part of our delta it hasn't already covered
                cur = len(strings)
                if cur < have + len(r["ids"]):
                    strings.extend(r["ids"][cur - have:])
        return RemoteInterner(strings)

    def write_relationships(self, ops: list,
                            preconditions: list = ()) -> int:
        return self._call(
            "write_relationships",
            ops=[{"op": o.op, "rel": _rel_to_dict(o.rel)} for o in ops],
            preconditions=[{"filter": asdict(p.filter),
                            "must_exist": p.must_exist}
                           for p in preconditions])

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list = ()) -> int:
        return self._call(
            "delete_relationships", filter=asdict(f),
            preconditions=[{"filter": asdict(p.filter),
                            "must_exist": p.must_exist}
                           for p in preconditions])

    def read_relationships(self, f: RelationshipFilter):
        return [_rel_from_dict(d)
                for d in self._call("read_relationships", filter=asdict(f))]

    def watch_since(self, revision: int) -> list:
        return [
            WatchEvent(d["revision"], d["operation"],
                       _rel_from_dict(d["rel"]))
            for d in self._call("watch_since", revision=revision)
        ]

    def watch_push_stream(self, from_revision: int) -> RemoteWatchStream:
        """Open a server-push event subscription (dedicated connection).
        The watch hub prefers this over polling ``watch_since`` — zero
        steady-state request traffic per engine (not per watcher)."""
        return RemoteWatchStream(self, from_revision)

    def watch_gate(self, resource_type: str, name: str
                   ) -> tuple[Optional[frozenset], bool]:
        """Schema-derived recompute gate for watches, fetched from the
        engine host (which owns the schema). (None, True) against an
        older host that lacks the op — callers then recompute
        unconditionally and keep the expiry tick (the safe direction)."""
        try:
            r = self._call("watch_gate", resource_type=resource_type,
                           name=name)
            return frozenset(r["types"]), bool(r["use_expiration"])
        except RemoteEngineError:
            return None, True

    # -- rebalance slice ops (idempotent mover data plane) -------------------

    def slice_read(self, ranges, want_globals: bool = False):
        """(src_revision, [Relationship...]) for the hash ranges — one
        npz binary frame, not a JSON row list."""
        from ..persistence.codec import decode_bulk_cols

        r = self._call_any("slice_read",
                           ranges=[[int(lo), int(hi)]
                                   for lo, hi in ranges],
                           **{"globals": bool(want_globals)})
        if not isinstance(r, tuple):
            raise RemoteEngineError(
                f"slice_read answered a non-binary frame: {r!r}")
        meta, payload = r
        return int(meta["revision"]), _cols_to_rels(
            decode_bulk_cols(payload))

    def slice_load(self, rels) -> int:
        """Idempotent TOUCH import of exported rows; returns the
        destination revision after the load."""
        import base64

        from ..persistence.codec import encode_bulk_cols

        r = self._call("slice_load", payload_b64=base64.b64encode(
            encode_bulk_cols(_rels_to_cols(list(rels)))).decode())
        return int(r["revision"])

    def slice_apply(self, ops) -> int:
        """Catch-up replay of concrete touch/delete effects."""
        r = self._call("slice_apply",
                       ops=[{"op": o.op, "rel": _rel_to_dict(o.rel)}
                            for o in ops])
        return int(r["revision"])

    def slice_drop(self, ranges) -> int:
        """Post-cutover GC of the moved rows; returns rows dropped."""
        r = self._call("slice_drop",
                       ranges=[[int(lo), int(hi)]
                               for lo, hi in ranges])
        return int(r["rows"])

    def slice_watch_since(self, revision: int) -> list:
        """The mover's catch-up poll: ``watch_since`` under the
        rebalance admission class; falls back to the tenant op against
        hosts predating it (same answer, old cost class)."""
        try:
            frames = self._call("slice_watch", revision=revision)
        except EngineInternalError:
            raise
        except RemoteEngineError:
            return self.watch_since(revision)
        return [
            WatchEvent(d["revision"], d["operation"],
                       _rel_from_dict(d["rel"]))
            for d in frames
        ]

    @property
    def revision(self) -> int:
        return self._call("revision")

    def failover_state(self) -> dict:
        """Replication role/term/revision of this endpoint (one
        single-attempt round trip — deliberately NOT in the idempotent
        retry set: resolution probes must answer fast about dead hosts,
        not burn a retry budget against them)."""
        return self._call("failover_state")

    def fetch_traces(self, limit: int = 64) -> list:
        """The engine host's recent kept-trace ring (trace fragments
        sharing the proxy's trace_ids); [] against hosts predating the
        op — trace retrieval is diagnostics, never an error."""
        try:
            return self._call("traces", limit=limit) or []
        except RemoteEngineError:
            return []

    # chaos control plane (single-attempt like failover_state: arming a
    # fault must not itself burn the retry budget it is about to test)

    def chaos_arm(self, schedule_doc: dict) -> dict:
        """Arm a fault schedule on the host (requires the host's
        --enable-chaos-ops); returns {armed, digest}."""
        return self._call("chaos_arm", schedule=schedule_doc)

    def chaos_reset(self) -> dict:
        return self._call("chaos_reset")

    def chaos_status(self) -> dict:
        return self._call("chaos_status")

    # live schema migration control plane (migration/migrator.py)

    def migrate_begin(self, schema_text: str, *,
                      hold_at_dual: Optional[bool] = None,
                      batch: Optional[int] = None,
                      backfill_pause: Optional[float] = None,
                      parity_samples: Optional[int] = None,
                      wait: bool = False) -> dict:
        """Begin a live schema migration on the host. Single-attempt
        (NOT idempotent: a replay would race the host's single-active-
        migration refusal); an incompatible change surfaces as the
        host's typed SchemaError before any state change."""
        return self._call(
            "migrate_begin", schema_text=schema_text,
            hold_at_dual=hold_at_dual, batch=batch,
            backfill_pause=backfill_pause,
            parity_samples=parity_samples, wait=wait)

    def migrate_status(self) -> Optional[dict]:
        return self._call("migrate_status")

    def migrate_cut(self, wait: bool = True) -> dict:
        """Release a ``hold_at_dual`` migration into its cut
        (idempotent — the planner's coordinated-cut hook retries this
        through leader churn)."""
        return self._call("migrate_cut", wait=wait)

    def migrate_abort(self) -> dict:
        return self._call("migrate_abort")


# -- client-side engine failover ----------------------------------------------


class _PrimaryBreakerView:
    """The breaker surface (/readyz reasons, dual-write fast-fail) of
    whichever endpoint is CURRENTLY primary. A dead former leader's
    permanently-open breaker must not keep a successfully failed-over
    replica unready forever."""

    def __init__(self, fe: "FailoverEngine"):
        self._fe = fe

    @property
    def dependency(self) -> str:
        return self._fe._primary().breaker.dependency

    def open_reason(self):
        return self._fe._primary().breaker.open_reason()

    def check_open(self) -> None:
        self._fe._primary().breaker.check_open()


class _FailoverStoreShim:
    """The sliver of Store the proxy touches, over the failover client."""

    def __init__(self, fe: "FailoverEngine"):
        self._fe = fe

    def exists(self, f: RelationshipFilter) -> bool:
        return self._fe._invoke(lambda c: c.store.exists(f))


class FailoverEngine:
    """A RemoteEngine over a LIST of engine endpoints (``--engine-endpoint
    tcp://h1:p1,h2:p2,...``): every call goes to the current primary;
    when the primary stops answering — transport death, open breaker,
    exhausted deadline, or a role-gated ``not_leader`` rejection — the
    client re-resolves by probing every endpoint's ``failover_state``
    and re-aims at the leader with the highest term.

    Retry discipline under failover mirrors the single-endpoint client's:
    reads re-issue against the new primary transparently; writes re-issue
    ONLY when the failed attempt provably never dispatched (a not_leader
    rejection or an open breaker) — a write that died mid-transport may
    have been applied and surfaces its error instead. While no leader is
    reachable, calls raise :class:`~..utils.resilience.
    DependencyUnavailable`, which the authz middleware maps to the
    fail-closed kube 503 + Retry-After."""

    def __init__(self, endpoints: list, token: Optional[str] = None,
                 probe_timeout: float = 5.0,
                 resolve_deadline: float = 30.0, **client_kw):
        if not endpoints:
            raise RemoteEngineError("failover engine needs >= 1 endpoint")
        self.endpoints = [(h, int(p)) for h, p in endpoints]
        self.token = token
        # ONE retry budget spans the whole failover stack: per-endpoint
        # transport retries AND this layer's re-issues draw from the
        # same bucket, so a dead/browned-out set can't amplify load by
        # layers × retries (utils/resilience.py RetryBudget)
        self.retry_budget = client_kw.get("retry_budget")
        self._clients = [RemoteEngine(h, p, token=token, **client_kw)
                         for h, p in self.endpoints]
        # dedicated probe clients: short budgets, single attempt, and a
        # breaker that never opens — resolution must stay able to ask a
        # freshly-recovered host "are you the leader yet?" even after
        # thousands of failed probes. NO retry budget: probes are how
        # resolution heals, and their deposits/withdrawals would distort
        # the data-path budget.
        probe_kw = dict(client_kw)
        probe_kw.pop("breaker", None)
        probe_kw.pop("retry_budget", None)
        probe_kw["timeout"] = probe_timeout
        probe_kw["connect_timeout"] = min(
            probe_timeout, client_kw.get("connect_timeout", probe_timeout))
        probe_kw["retries"] = 0
        self._probes = [
            RemoteEngine(h, p, token=token,
                         breaker=CircuitBreaker(
                             f"engine-probe:{h}:{p}",
                             failure_threshold=1 << 30),
                         **probe_kw)
            for h, p in self.endpoints]
        self._resolve_deadline = resolve_deadline
        self._lock = threading.Lock()
        self._primary_idx = 0
        self._last_status: dict = {}
        # resolution singleflight: during a failover every blocked
        # request thread wants a resolution pass; one prober at a time
        # runs it and waiters piggyback on its outcome instead of
        # stampeding N-endpoint probe storms at the surviving host
        self._resolve_flight = threading.Lock()
        self._resolve_gen = 0
        self._resolve_ok = False
        # monotonic term floor: once this client has SEEN term T, no
        # endpoint claiming leadership at a lower term is ever followed
        # again — a deposed leader partitioned away from its peers still
        # answers "leader", and aiming reads at its fenced-off state
        # would serve stale verdicts (fail closed instead)
        self._max_term = 0
        self.dependency = "engine-failover:" + ",".join(
            f"{h}:{p}" for h, p in self.endpoints)
        self.breaker = _PrimaryBreakerView(self)
        self.store = _FailoverStoreShim(self)

    def _primary(self) -> RemoteEngine:
        with self._lock:
            return self._clients[self._primary_idx]

    # -- resolution ----------------------------------------------------------

    def _resolve(self) -> bool:
        """One resolution pass, singleflighted: callers that arrive
        while another thread is mid-pass wait for IT and share its
        outcome rather than launching a redundant probe storm."""
        gen = self._resolve_gen
        with self._resolve_flight:
            if self._resolve_gen != gen:
                return self._resolve_ok  # piggyback on the finished pass
            ok = self._resolve_once()
            self._resolve_gen += 1
            self._resolve_ok = ok
            return ok

    def _resolve_once(self) -> bool:
        """Probe every endpoint once and re-aim at the best reachable
        LEADER (highest term; ties by list order). Probing happens
        OUTSIDE the primary-index lock — healthy callers reading the
        index must not stall behind a resolution pass's connect
        timeouts."""
        t0 = time.monotonic()
        states = []
        for i, probe in enumerate(self._probes):
            try:
                st = probe.failover_state()
            except Exception as e:  # noqa: BLE001 - unreachable peer
                log.debug("failover probe %s:%s failed: %s",
                          *self.endpoints[i], e)
                continue
            states.append((i, st))
            self._max_term = max(self._max_term,
                                 int(st.get("term", 0) or 0))
        best = None
        for i, st in states:
            if st.get("role") != "leader":
                continue
            term = int(st.get("term", 0) or 0)
            if term < self._max_term:
                # a reachable-but-deposed leader (partitioned from its
                # peers, so it never demoted): following it would serve
                # its fenced-off lineage — stay unresolved (fail closed)
                log.warning(
                    "ignoring %s:%s claiming leadership at deposed term "
                    "%d (highest seen: %d)", *self.endpoints[i], term,
                    self._max_term)
                continue
            key = (-term, i)
            if best is None or key < best[0]:
                best = (key, i, st)
        if best is None:
            return False
        _, idx, st = best
        with self._lock:
            old = self._primary_idx
            self._primary_idx = idx
            self._last_status = dict(st)
        if idx != old:
            metrics.counter("failover_total").inc()
            metrics.histogram("failover_duration_seconds").observe(
                time.monotonic() - t0)
            log.warning(
                "engine failover: primary %s:%s -> %s:%s (term %s)",
                *self.endpoints[old], *self.endpoints[idx],
                st.get("term"))
        return True

    def _invoke(self, call, write: bool = False):
        c = self._primary()
        try:
            return call(c)
        except AdmissionRejected:
            # a healthy-but-overloaded leader shed the op: re-aiming at a
            # follower cannot help (it would only answer not_leader), and
            # a probe storm would add load to exactly the wrong host —
            # surface the shed (503 + Retry-After) immediately
            raise
        except NotLeaderError as e:
            cause, retry_ok = e, True  # rejected BEFORE dispatch
        except DependencyUnavailable as e:
            # BreakerOpen = no attempt reached the wire (safe even for a
            # write); an exhausted deadline may have dispatched
            from ..utils.resilience import BreakerOpen

            cause, retry_ok = e, (not write) or isinstance(e, BreakerOpen)
        except TRANSPORT_ERRORS as e:
            cause, retry_ok = e, not write
        if not retry_ok:
            # the outcome cannot change by waiting (the write MAY have
            # been applied): kick ONE resolution pass so the system
            # heals for subsequent calls, then surface the truth now —
            # never park a kube write for a whole election window just
            # to raise the same error
            self._resolve()
            raise cause
        # re-resolve (bounded by resolve_deadline — an election takes
        # heartbeat-timeout + promotion time) and re-issue. The re-issue
        # is a RETRY of the logical op: it draws from the shared budget,
        # so a whole fleet re-aiming at a browned-out set stays bounded.
        if self.retry_budget is not None and not self.retry_budget.allow():
            raise DependencyUnavailable(
                self.dependency,
                f"retry budget for {self.dependency} exhausted during "
                "failover re-aim",
                retry_after=1.0) from cause
        deadline = time.monotonic() + self._resolve_deadline
        while not self._resolve():
            if time.monotonic() >= deadline:
                raise DependencyUnavailable(
                    self.dependency,
                    "no engine replication leader reachable among "
                    f"{len(self.endpoints)} endpoints "
                    "(failover in progress?)",
                    retry_after=1.0) from cause
            time.sleep(0.2)
        return call(self._primary())

    # -- engine surface (the slice the proxy consumes) -----------------------

    def check(self, item: CheckItem, now: Optional[float] = None,
              context: Optional[dict] = None) -> bool:
        return self.check_bulk([item], now=now, context=context)[0]

    def check_bulk(self, items: list, now: Optional[float] = None,
                   context: Optional[dict] = None) -> list:
        return self._invoke(lambda c: c.check_bulk(items, now=now,
                                                   context=context))

    def lookup_subjects(self, resource_type: str, resource_id: str,
                        permission: str, subject_type: str,
                        subject_relation: Optional[str] = None,
                        now: Optional[float] = None,
                        context: Optional[dict] = None) -> list:
        return self._invoke(lambda c: c.lookup_subjects(
            resource_type, resource_id, permission, subject_type,
            subject_relation, now=now, context=context))

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None,
                         context: Optional[dict] = None) -> list:
        return self._invoke(lambda c: c.lookup_resources(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context))

    def lookup_resources_mask(self, resource_type: str, permission: str,
                              subject_type: str, subject_id: str,
                              subject_relation: Optional[str] = None,
                              now: Optional[float] = None,
                              context: Optional[dict] = None):
        return self._invoke(lambda c: c.lookup_resources_mask(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context))

    def load_status(self) -> dict:
        return self._invoke(lambda c: c.load_status())

    def frontier_pairs(self) -> tuple:
        return self._invoke(lambda c: c.frontier_pairs())

    def frontier_expand(self, descs, pairs,
                        now: Optional[float] = None,
                        context: Optional[dict] = None) -> set:
        return self._invoke(lambda c: c.frontier_expand(
            descs, pairs, now=now, context=context))

    def write_relationships(self, ops: list,
                            preconditions: list = ()) -> int:
        return self._invoke(
            lambda c: c.write_relationships(ops, preconditions),
            write=True)

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list = ()) -> int:
        return self._invoke(
            lambda c: c.delete_relationships(f, preconditions),
            write=True)

    def read_relationships(self, f: RelationshipFilter):
        return self._invoke(lambda c: c.read_relationships(f))

    def watch_since(self, revision: int) -> list:
        return self._invoke(lambda c: c.watch_since(revision))

    def watch_push_stream(self, from_revision: int) -> RemoteWatchStream:
        return self._invoke(lambda c: c.watch_push_stream(from_revision))

    def watch_gate(self, resource_type: str, name: str):
        return self._invoke(lambda c: c.watch_gate(resource_type, name))

    # rebalance slice ops: idempotent by construction, so they follow
    # the READ re-issue discipline — after a transport death or a
    # not_leader rejection (a SIGKILL'd group leader mid-copy), the
    # re-aimed re-issue converges instead of double-applying
    def slice_read(self, ranges, want_globals: bool = False):
        return self._invoke(
            lambda c: c.slice_read(ranges, want_globals=want_globals))

    def slice_load(self, rels) -> int:
        return self._invoke(lambda c: c.slice_load(rels))

    def slice_apply(self, ops) -> int:
        return self._invoke(lambda c: c.slice_apply(ops))

    def slice_drop(self, ranges) -> int:
        return self._invoke(lambda c: c.slice_drop(ranges))

    def slice_watch_since(self, revision: int) -> list:
        return self._invoke(lambda c: c.slice_watch_since(revision))

    # migration control plane: begin follows the WRITE discipline (no
    # re-issue after an ambiguous death — a replay races the host's
    # single-active-migration refusal); status/cut/abort are
    # level-triggered and re-aim like reads
    def migrate_begin(self, schema_text: str, **kw) -> dict:
        return self._invoke(lambda c: c.migrate_begin(schema_text, **kw),
                            write=True)

    def migrate_status(self) -> Optional[dict]:
        return self._invoke(lambda c: c.migrate_status())

    def migrate_cut(self, wait: bool = True) -> dict:
        return self._invoke(lambda c: c.migrate_cut(wait=wait))

    def migrate_abort(self) -> dict:
        return self._invoke(lambda c: c.migrate_abort())

    def fetch_traces(self, limit: int = 64) -> list:
        """Trace fragments from EVERY reachable endpoint (a re-aimed
        request leaves spans on more than one host); per-endpoint
        failures contribute nothing rather than failing diagnostics."""
        out: list = []
        for c in self._clients:
            try:
                out.extend(c.fetch_traces(limit))
            except Exception:  # noqa: BLE001 - diagnostics best-effort
                continue
        return out

    def chaos_arm(self, schedule_doc: dict) -> dict:
        """Arm a fault schedule on EVERY reachable endpoint of the set
        (a campaign targets the whole replication group — the fault must
        survive a failover). Returns {endpoint: result-or-error}."""
        out: dict = {}
        for c in self._clients:
            try:
                out[c.dependency] = c.chaos_arm(schedule_doc)
            except Exception as e:  # noqa: BLE001 - report per endpoint
                out[c.dependency] = {"error": repr(e)}
        return out

    def chaos_reset(self) -> dict:
        out: dict = {}
        for c in self._clients:
            try:
                out[c.dependency] = c.chaos_reset()
            except Exception as e:  # noqa: BLE001 - report per endpoint
                out[c.dependency] = {"error": repr(e)}
        return out

    @property
    def revision(self) -> int:
        return self._invoke(lambda c: c.revision)

    def _probe_primary(self) -> Optional[dict]:
        c = self._primary()
        if c.breaker.open_reason() is not None:
            return None  # known-dead: don't stack a connect timeout
        try:
            st = self._probes[self._clients.index(c)].failover_state()
        except Exception:  # noqa: BLE001 - unreachable primary
            return None
        term = int(st.get("term", 0) or 0)
        self._max_term = max(self._max_term, term)
        if st.get("role") != "leader" or term < self._max_term:
            return None  # demoted, or a deposed straggler still leading
        with self._lock:
            self._last_status = dict(st)
        return st

    def replication_status(self) -> dict:
        """{role, term, lag} of the current primary, for /readyz. When
        the primary looks dead or demoted, attempt a resolution pass
        first: an IDLE proxy has no data traffic to trigger _invoke's
        re-resolve, and without this its /readyz would stay unready
        forever after a failover — unreadiness would then keep the
        traffic away that could have healed it (the same trap the
        breaker's probe-eligible /readyz rule avoids)."""
        st = self._probe_primary()
        if st is None and self._resolve():
            st = self._probe_primary()
        if st is None:
            return {"role": "electing",
                    "term": self._last_status.get("term"), "lag": None}
        return {"role": st.get("role"), "term": st.get("term"),
                "lag": st.get("lag")}

    def close(self) -> None:
        for c in self._clients + self._probes:
            c.close()


def main(argv=None) -> int:
    """Standalone engine host: ``python -m
    spicedb_kubeapi_proxy_tpu.engine.remote --bootstrap schema.yaml
    --bind-port 50051`` — the TPU-owning process proxies connect to."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(prog="sdbkp-engine",
                                 description="TPU engine host")
    ap.add_argument("--bootstrap", action="append", default=[],
                    help="schema/relationships bootstrap YAML (repeatable)")
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--bind-port", type=int, default=50051)
    ap.add_argument("--token", help="shared bearer token")
    # transport security (reference remote-endpoint flag shape,
    # options.go:325-369): TLS is the default posture — serving requires
    # a cert/key pair, and plaintext requires an explicit opt-out
    ap.add_argument("--tls-cert-file",
                    help="serving certificate (PEM); enables TLS")
    ap.add_argument("--tls-key-file",
                    help="serving private key (PEM)")
    ap.add_argument("--tls-client-ca-file",
                    help="require client certificates signed by this CA "
                         "(mutual TLS, on top of the token)")
    ap.add_argument("--engine-insecure", action="store_true",
                    help="serve PLAINTEXT TCP (and dial the mirror "
                         "leader plaintext) — tokens and relationships "
                         "transit in the clear; never use across hosts")
    ap.add_argument("--mirror-ca-file",
                    help="(follower) CA bundle for verifying the mirror "
                         "leader's certificate (default: system store)")
    ap.add_argument("--mirror-skip-verify-ca", action="store_true",
                    help="(follower) TLS to the leader without "
                         "certificate verification")
    ap.add_argument("--snapshot-path",
                    help="relationship-store snapshot: loaded at boot if "
                         "present, saved on graceful shutdown (superseded "
                         "by --data-dir, which also survives SIGKILL)")
    ap.add_argument("--data-dir",
                    help="durable persistence directory (persistence/): "
                         "write-ahead log + snapshot checkpoints; crash "
                         "recovery replays the WAL tail at boot. Unset = "
                         "in-memory store (today's behavior)")
    ap.add_argument("--wal-fsync", default="interval:100",
                    help="WAL fsync policy: always | interval:<ms> | off "
                         "(default interval:100)")
    ap.add_argument("--checkpoint-wal-bytes", type=int, default=64 << 20,
                    help="snapshot-checkpoint the store once this many "
                         "WAL bytes accumulate since the last checkpoint")
    ap.add_argument("--checkpoint-wal-records", type=int, default=50000,
                    help="...or this many WAL records, whichever first")
    ap.add_argument("--checkpoint-keep", type=int, default=2,
                    help="snapshot generations to retain (the WAL is "
                         "pruned only up to the OLDEST kept one, so "
                         "recovery can fall back a generation)")
    ap.add_argument("--engine-mesh",
                    help="device mesh for this host's chips: 'auto' or "
                         "'data=D,graph=G' (the engine host owns the mesh; "
                         "proxies connect with tcp://)")
    ap.add_argument("--distributed",
                    help="multi-host: coordinator_host:port,"
                         "num_processes,process_id — joins "
                         "jax.distributed; with --engine-mesh auto the "
                         "mesh spans every process's devices. Process 0 "
                         "serves; others follow its mirror stream")
    ap.add_argument("--mirror-leader",
                    help="(follower processes) host:port of process 0's "
                         "engine endpoint to subscribe to")
    ap.add_argument("--peers",
                    help="replicated-set mode with AUTOMATIC leader "
                         "failover: comma-separated host:port of EVERY "
                         "engine host in the set, in peer-id order "
                         "(mutually exclusive with --distributed; see "
                         "docs/operations.md 'Leader failover')")
    ap.add_argument("--peer-id", type=int, default=0,
                    help="this process's index into --peers")
    ap.add_argument("--mirror-heartbeat-seconds", type=float, default=2.0,
                    help="(--peers) leader heartbeat cadence on the "
                         "mirror stream; followers detect a dead leader "
                         "within ~3x this")
    ap.add_argument("--mirror-heartbeat-timeout", type=float, default=0.0,
                    help="(--peers) follower's dead-leader window "
                         "(0 = 3x heartbeat + 1s)")
    ap.add_argument("--replication-timeout", type=float, default=10.0,
                    help="(--peers) how long an acked write waits for "
                         "follower acknowledgement before the laggard "
                         "is dropped to catch-up")
    ap.add_argument("--min-sync-replicas", type=int, default=0,
                    help="(--peers) durability floor: with fewer live "
                         "followers than this, writes FAIL CLOSED "
                         "instead of acking unreplicated (0 = keep "
                         "serving when the last follower dies — "
                         "availability over redundancy)")
    ap.add_argument("--failover-boot-grace", type=float, default=20.0,
                    help="(--peers) boot-time wait for the rest of the "
                         "set before electing from partial visibility")
    ap.add_argument("--lookup-batch-window", type=float, default=0.0,
                    help="fuse concurrent lookup_mask requests (across "
                         "ALL connected proxies) into shared device "
                         "dispatches, holding each for at most this many "
                         "seconds (0 = off). No effect on --distributed "
                         "hosts: mirrored lookups pin their evaluation "
                         "time for SPMD lockstep, which bypasses fusion")
    from ..proxy.options import parse_bool_flag

    ap.add_argument("--authz-cache", type=parse_bool_flag, nargs="?",
                    const=True, default=True, metavar="BOOL",
                    help="revision-keyed decision cache + singleflight: "
                         "identical checks/lookups at an unchanged "
                         "revision serve host-side, shared across ALL "
                         "connected proxy replicas (default on). No "
                         "effect on --distributed hosts: mirrored "
                         "queries pin their evaluation time, which "
                         "bypasses the cache")
    ap.add_argument("--authz-cache-size", type=int, default=65536,
                    help="max cached decisions (LRU entries)")
    ap.add_argument("--authz-cache-mask-bytes", type=int,
                    default=256 << 20,
                    help="resident lookup-mask byte budget")
    ap.add_argument("--delta-capacity", type=int, default=4096,
                    help="device-resident delta-overlay slots per "
                         "compiled graph (fixed jit signature; size to "
                         "the write burst one compaction interval must "
                         "absorb)")
    ap.add_argument("--compact-threshold", type=float, default=0.75,
                    help="overlay-occupancy fraction that wakes the "
                         "background compactor; a full overlay sheds "
                         "writes with a bounded Retry-After (rides the "
                         "kind='admission' frame — breakers stay "
                         "closed) instead of stalling reads on a "
                         "synchronous recompile (0 disables)")
    ap.add_argument("--admission", type=parse_bool_flag, nargs="?",
                    const=True, default=False, metavar="BOOL",
                    help="admission control (admission/): cost-classed, "
                         "per-tenant (= proxy replica) fair queueing with "
                         "an adaptive concurrency limit and priority load "
                         "shedding in front of the dispatch pool — "
                         "protects a shared engine host from the "
                         "aggregate of many proxy replicas (default off)")
    ap.add_argument("--admission-initial-concurrency", type=float,
                    default=32.0,
                    help="adaptive limiter's starting weighted-cost limit")
    ap.add_argument("--admission-min-concurrency", type=float, default=4.0)
    ap.add_argument("--admission-max-concurrency", type=float,
                    default=512.0)
    ap.add_argument("--admission-tenant-rate", type=float, default=50.0,
                    help="per-tenant fair-share refill (cost units/s)")
    ap.add_argument("--admission-tenant-burst", type=float, default=100.0,
                    help="per-tenant debt cap (cost units a storm is "
                         "remembered for)")
    ap.add_argument("--admission-tenant-queue-depth", type=int, default=32)
    ap.add_argument("--admission-queue-depth", type=int, default=256,
                    help="global queued-request bound; past it the "
                         "lowest-priority class sheds first")
    ap.add_argument("--admission-queue-timeout", type=float, default=1.0,
                    help="max seconds a request may queue before it is "
                         "shed (503 + Retry-After, never a hang)")
    ap.add_argument("--trace-sample", type=float, default=0.1,
                    help="tail-sampling keep probability for engine-host "
                         "trace fragments (error/slow ops always kept; "
                         "0 disables span recording entirely). Proxies "
                         "forward their trace context on the wire; "
                         "fragments share the proxy's trace_id")
    ap.add_argument("--trace-slow-ms", type=float, default=250.0,
                    help="ops at or above this duration are always kept "
                         "by tail sampling")
    ap.add_argument("--enable-chaos-ops", action="store_true",
                    help="TEST ONLY: accept chaos_arm/chaos_reset/"
                         "chaos_status wire ops that install seeded "
                         "fault schedules (error/drop/delay/crash) into "
                         "this process's failpoint registry — how the "
                         "chaos campaign drives deterministic faults on "
                         "subprocess engine hosts. Never enable in "
                         "production")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if not 0.0 <= args.trace_sample <= 1.0:
        ap.error("--trace-sample must be in [0, 1]")
    tracer.configure(sample=args.trace_sample,
                     slow_ms=args.trace_slow_ms)

    from ..utils.tlsconf import (
        TLSConfigError,
        client_ssl_context,
        server_ssl_context,
    )

    if bool(args.tls_cert_file) != bool(args.tls_key_file):
        ap.error("--tls-cert-file and --tls-key-file go together")
    if args.engine_insecure and args.tls_cert_file:
        ap.error("--engine-insecure and --tls-cert-file are mutually "
                 "exclusive")
    from .compaction import validate_overlay_config

    try:
        # shared validator (also behind proxy/options.py): clean flag
        # error at boot, not a constructor traceback
        validate_overlay_config(args.delta_capacity,
                                args.compact_threshold)
    except ValueError as e:
        ap.error(str(e))
    if args.admission:
        # shared validator (admission.validate_config, also behind
        # proxy/options.py): misconfiguration is a clean flag error at
        # boot, not a raw constructor traceback or a silently-degenerate
        # fair queue (rate 0 never forgives debt)
        from ..admission import validate_config

        try:
            validate_config(
                args.admission_initial_concurrency,
                args.admission_min_concurrency,
                args.admission_max_concurrency,
                args.admission_tenant_rate, args.admission_tenant_burst,
                args.admission_tenant_queue_depth,
                args.admission_queue_depth, args.admission_queue_timeout)
        except ValueError as e:
            ap.error(str(e))
    peers = None
    if args.peers:
        from ..parallel.failover import FailoverError, parse_peers

        if args.distributed:
            ap.error("--peers (automatic failover) and --distributed "
                     "(SPMD lockstep) are mutually exclusive deployment "
                     "shapes")
        try:
            peers = parse_peers(args.peers)
        except FailoverError as e:
            ap.error(str(e))
        if not 0 <= args.peer_id < len(peers):
            ap.error(f"--peer-id {args.peer_id} out of range for "
                     f"{len(peers)} peers")
        if args.mirror_heartbeat_seconds <= 0:
            ap.error("--mirror-heartbeat-seconds must be > 0")
    # a mirror FOLLOWER never serves — it only dials the leader — so the
    # refuse-plaintext-serving check must not force cert/key on it
    is_follower = False
    if args.distributed:
        from ..parallel.multihost import (
            MultiHostError,
            parse_distributed_spec,
        )

        try:
            _, _, _spec_pid = parse_distributed_spec(args.distributed)
        except MultiHostError as e:
            ap.error(str(e))
        is_follower = _spec_pid > 0 and bool(args.mirror_leader)
    server_ssl = None
    if args.tls_cert_file:
        try:
            server_ssl = server_ssl_context(args.tls_cert_file,
                                            args.tls_key_file,
                                            args.tls_client_ca_file)
        except TLSConfigError as e:
            ap.error(str(e))
    elif not args.engine_insecure and not is_follower:
        ap.error("refusing to serve plaintext TCP: pass --tls-cert-file/"
                 "--tls-key-file, or --engine-insecure to opt out "
                 "explicitly (the token and every relationship would "
                 "transit in the clear)")
    mirror_ssl = None
    if not args.engine_insecure:
        try:
            mirror_ssl = client_ssl_context(
                args.mirror_ca_file, args.mirror_skip_verify_ca)
        except TLSConfigError as e:
            ap.error(str(e))

    process_id = 0
    if args.distributed:
        from ..parallel.multihost import MultiHostError, init_distributed

        try:
            init_distributed(args.distributed)
        except MultiHostError as e:
            ap.error(str(e))
        import jax as _jax

        process_id = _jax.process_index()
        log.info("distributed: process %d of %d", process_id,
                 _jax.process_count())
        if process_id > 0 and not args.mirror_leader:
            ap.error("follower processes need --mirror-leader host:port")
    mesh = None
    if args.engine_mesh:
        from ..parallel import make_mesh
        from ..parallel.mesh import parse_mesh_spec

        try:
            mesh = make_mesh(**parse_mesh_spec(args.engine_mesh))
        except ValueError as e:  # MeshSpecError or axis/device mismatch
            ap.error(str(e))
        log.info("engine mesh: %s", dict(mesh.shape))
    if args.data_dir and args.snapshot_path:
        ap.error("--data-dir and --snapshot-path are mutually exclusive "
                 "(the data dir owns snapshots AND the write-ahead log)")
    from ..persistence.wal import WalError, parse_fsync_policy

    if args.data_dir:
        try:
            parse_fsync_policy(args.wal_fsync)
        except WalError as e:
            ap.error(str(e))
    bootstrap = "\n---\n".join(open(f).read() for f in args.bootstrap) or None
    engine = Engine(bootstrap=bootstrap, mesh=mesh,
                    delta_capacity=args.delta_capacity)
    if args.compact_threshold > 0:
        engine.enable_compaction(args.compact_threshold)
        log.info("overlay compaction on: capacity %d, threshold %.2f",
                 args.delta_capacity, args.compact_threshold)
    persistence = None
    if args.data_dir:
        persistence = engine.enable_persistence(
            args.data_dir, wal_fsync=args.wal_fsync,
            checkpoint_wal_bytes=args.checkpoint_wal_bytes,
            checkpoint_wal_records=args.checkpoint_wal_records,
            checkpoint_keep=args.checkpoint_keep)
        log.info("persistence: %s (recovered revision %d, %d WAL "
                 "records replayed)", args.data_dir,
                 persistence.recovery.revision,
                 persistence.recovery.replayed_records)
        # boot crash matrix for a live schema migration killed mid-flight
        # (migration/migrator.py): no persisted cut -> clean abort, cut
        # persisted -> finish the cutover under the new schema
        mig = engine.recover_schema_migration()
        if mig is not None:
            log.info("schema migration record recovered: %s (phase %s)",
                     mig.get("action"), mig.get("phase"))
    if args.lookup_batch_window > 0:
        engine.enable_lookup_batching(args.lookup_batch_window)
    if args.authz_cache:
        engine.enable_decision_cache(
            max_entries=args.authz_cache_size,
            max_mask_bytes=args.authz_cache_mask_bytes)
    if engine.load_snapshot_if_exists(args.snapshot_path):
        log.info("loaded snapshot %s (revision %d)", args.snapshot_path,
                 engine.revision)
    if args.distributed and process_id > 0:
        # follower: replay the leader's mirror stream until it ends; a
        # persistent follower resumes from its own recovered revision
        # (the leader catches it up from its watch history / a state
        # transfer instead of requiring a process-lifetime stream)
        from ..parallel.multihost import follower_loop

        host, _, port = args.mirror_leader.rpartition(":")
        log.info("following leader %s:%s%s", host, port,
                 " (TLS)" if mirror_ssl else "")
        try:
            follower_loop(engine, host, int(port), token=args.token,
                          ssl_context=mirror_ssl,
                          from_revision=(engine.revision
                                         if persistence is not None
                                         else None))
        finally:
            engine.close_persistence()
        return 0
    if args.distributed:
        from ..parallel.multihost import MirroredEngine

        # the join barrier: refuse to execute anything until every
        # follower has subscribed (n-1 of them)
        engine = MirroredEngine(
            engine, min_subscribers=_jax.process_count() - 1)
    admission = None
    if args.admission:
        from ..admission import AdmissionController

        admission = AdmissionController(
            initial_concurrency=args.admission_initial_concurrency,
            min_concurrency=args.admission_min_concurrency,
            max_concurrency=args.admission_max_concurrency,
            tenant_rate=args.admission_tenant_rate,
            tenant_burst=args.admission_tenant_burst,
            tenant_depth=args.admission_tenant_queue_depth,
            global_depth=args.admission_queue_depth,
            queue_timeout=args.admission_queue_timeout,
            dependency="engine-admission")
        log.info("admission control on: limit %.0f (%.0f..%.0f), queue "
                 "%d/%d, timeout %.2fs",
                 args.admission_initial_concurrency,
                 args.admission_min_concurrency,
                 args.admission_max_concurrency,
                 args.admission_tenant_queue_depth,
                 args.admission_queue_depth,
                 args.admission_queue_timeout)
    if args.enable_chaos_ops:
        log.warning("chaos ops ENABLED: this host accepts wire-armed "
                    "fault schedules (test topologies only)")
    server = EngineServer(engine, args.bind_host, args.bind_port,
                          token=args.token, ssl_context=server_ssl,
                          admission=admission,
                          allow_chaos=args.enable_chaos_ops)
    coordinator = None
    if peers is not None:
        from ..parallel.failover import FailoverCoordinator

        coordinator = FailoverCoordinator(
            engine, server, peers, args.peer_id,
            token=args.token, data_dir=args.data_dir,
            heartbeat_interval=args.mirror_heartbeat_seconds,
            heartbeat_timeout=(args.mirror_heartbeat_timeout or None),
            replication_timeout=args.replication_timeout,
            min_sync_replicas=args.min_sync_replicas,
            client_ssl=mirror_ssl,
            boot_grace=args.failover_boot_grace)
        log.info("failover set: peer %d of %d (term %d)", args.peer_id,
                 len(peers), coordinator.term)

    async def serve():
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await server.start()
        if coordinator is not None:
            # the role state machine runs beside the asyncio server: it
            # swaps server.engine between the bare engine (follower,
            # role-gated) and the term-stamped mirror wrapper (leader)
            coordinator.start()
        await stop.wait()
        if coordinator is not None:
            coordinator.stop()
        await server.stop()
        if args.compact_threshold > 0:
            # stop the compactor before the final snapshot/checkpoint so
            # no fold races the state capture below
            await asyncio.get_running_loop().run_in_executor(
                None, engine.close_compaction)
        if args.snapshot_path:
            engine.save_snapshot(args.snapshot_path)
            log.info("saved snapshot to %s", args.snapshot_path)
        if persistence is not None:
            # final checkpoint + WAL fsync: the next boot loads one
            # snapshot and replays zero records
            await asyncio.get_running_loop().run_in_executor(
                None, engine.close_persistence)
            log.info("persistence closed (checkpointed %s)", args.data_dir)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
