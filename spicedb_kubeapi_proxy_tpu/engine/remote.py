"""Remote engine endpoint: the TPU engine served over TCP.

The reference proxy can point at a remote SpiceDB (`--spicedb-endpoint
host:port` with bearer token, /root/reference/pkg/proxy/options.go:325-369)
instead of the embedded one. This module is that deployment shape for the
TPU engine: one engine host owns the chip and N proxy replicas consume the
same engine API remotely — ``EngineServer`` wraps an in-process
:class:`Engine`; ``RemoteEngine`` is a drop-in client exposing the exact
surface the proxy consumes (check_bulk, lookup_resources,
write/read/delete relationships, watch_since, revision, store.exists).

Protocol: 4-byte big-endian length-prefixed JSON frames.
    request:  {"op": str, "token": str?, ...args}
    response: {"ok": true, "result": ...}
            | {"ok": false, "kind": str, "error": str}
Errors round-trip by kind so precondition failures and schema violations
keep their meaning across the wire (the dual-write activities branch on
them). Transport security is left to the surrounding infrastructure; a
shared bearer token gates requests like the reference's token option.
"""

from __future__ import annotations

import asyncio
import hmac
import json
import logging
import socket
import struct
import threading
from dataclasses import asdict
from typing import Optional

from ..utils.net import drain_server

from ..models.tuples import Relationship
from .engine import CheckItem, Engine, SchemaViolation, WatchEvent
from .store import (
    Precondition,
    PreconditionFailed,
    RelationshipFilter,
    StoreError,
    WriteOp,
)

log = logging.getLogger("sdbkp.engine.remote")

MAX_FRAME = 256 * 1024 * 1024
# Until a connection has authenticated once, frames are capped far smaller:
# an auth frame is a few hundred bytes, and the big limit exists for bulk
# relationship payloads that only authenticated peers may send. Without this
# an unauthenticated socket could make the server buffer 256MiB per frame.
MAX_FRAME_PREAUTH = 1024 * 1024

_ERROR_KINDS = {
    "precondition": PreconditionFailed,
    "schema": SchemaViolation,
    "store": StoreError,
}


class RemoteEngineError(RuntimeError):
    pass


# -- codecs ------------------------------------------------------------------


def _rel_to_dict(r: Relationship) -> dict:
    return asdict(r)


def _rel_from_dict(d: dict) -> Relationship:
    return Relationship(**d)


def _filter_from_dict(d: dict) -> RelationshipFilter:
    return RelationshipFilter(**d)


# -- framing -----------------------------------------------------------------


def _pack(msg: dict) -> bytes:
    body = json.dumps(msg).encode()
    return struct.pack(">I", len(body)) + body


async def _read_frame(reader: asyncio.StreamReader,
                      limit: int = MAX_FRAME) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = struct.unpack(">I", header)
    if n > limit:
        raise RemoteEngineError(f"frame of {n} bytes exceeds limit")
    body = await reader.readexactly(n)
    return json.loads(body)


# -- server ------------------------------------------------------------------


class EngineServer:
    """Serves an :class:`Engine` to remote proxies. Device queries run in
    worker threads (asyncio.to_thread) so slow fixpoints never stall other
    connections' dispatches — concurrent queries pipeline on the device the
    same way in-process callers do."""

    def __init__(self, engine: Engine, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None):
        self.engine = engine
        self.host = host
        self.port = port
        self.token = token
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()  # live connection-handler tasks

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("engine listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self, grace: float = 2.0) -> None:
        """Stop listening and drain connections (utils/net.py: clients
        pool idle sockets blocked in _read_frame, which ``wait_closed()``
        would wait on forever on Python 3.12+)."""
        if self._server is None:
            return
        await drain_server(self._server, self._conns, grace)
        self._server = None

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await self._serve_inner(reader, writer)
        finally:
            self._conns.discard(task)

    async def _serve_inner(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        authed = not self.token
        try:
            while True:
                limit = MAX_FRAME if authed else MAX_FRAME_PREAUTH
                req = await _read_frame(reader, limit=limit)
                if req is None:
                    return
                resp = await self._dispatch(req)
                if resp.get("ok") or resp.get("kind") != "auth":
                    authed = True
                writer.write(_pack(resp))
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("engine connection error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _dispatch(self, req: dict) -> dict:
        if self.token and not hmac.compare_digest(
                str(req.get("token") or ""), self.token):
            return {"ok": False, "kind": "auth", "error": "invalid token"}
        op = req.get("op")
        try:
            fn = getattr(self, f"_op_{op}", None)
            if fn is None:
                return {"ok": False, "kind": "proto",
                        "error": f"unknown op {op!r}"}
            result = await asyncio.to_thread(fn, req)
            return {"ok": True, "result": result}
        except PreconditionFailed as e:
            return {"ok": False, "kind": "precondition", "error": str(e)}
        except SchemaViolation as e:
            return {"ok": False, "kind": "schema", "error": str(e)}
        except StoreError as e:
            return {"ok": False, "kind": "store", "error": str(e)}
        except Exception as e:
            log.exception("engine op %s failed", op)
            return {"ok": False, "kind": "internal", "error": str(e)}

    # -- ops (run in worker threads) ----------------------------------------

    def _op_check_bulk(self, req: dict):
        items = [CheckItem(*it) for it in req["items"]]
        return self.engine.check_bulk(items, now=req.get("now"))

    def _op_lookup_resources(self, req: dict):
        return self.engine.lookup_resources(
            req["resource_type"], req["permission"], req["subject_type"],
            req["subject_id"], req.get("subject_relation"),
            now=req.get("now"))

    def _op_write_relationships(self, req: dict):
        ops = [WriteOp(o["op"], _rel_from_dict(o["rel"]))
               for o in req["ops"]]
        pcs = [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
               for p in req.get("preconditions", [])]
        return self.engine.write_relationships(ops, pcs)

    def _op_delete_relationships(self, req: dict):
        pcs = [Precondition(_filter_from_dict(p["filter"]), p["must_exist"])
               for p in req.get("preconditions", [])]
        return self.engine.delete_relationships(
            _filter_from_dict(req["filter"]), pcs)

    def _op_read_relationships(self, req: dict):
        return [_rel_to_dict(r) for r in self.engine.read_relationships(
            _filter_from_dict(req["filter"]))]

    def _op_watch_since(self, req: dict):
        return [
            {"revision": e.revision, "operation": e.operation,
             "rel": _rel_to_dict(e.relationship)}
            for e in self.engine.watch_since(req["revision"])
        ]

    def _op_watch_gate(self, req: dict):
        types, use_exp = self.engine.watch_gate(
            req["resource_type"], req["name"])
        return {"types": sorted(types), "use_expiration": use_exp}

    def _op_revision(self, req: dict):
        return self.engine.revision

    def _op_exists(self, req: dict):
        return self.engine.store.exists(_filter_from_dict(req["filter"]))


# -- client ------------------------------------------------------------------


class _StoreShim:
    """The sliver of Store the proxy touches remotely (idempotency-key and
    lock existence probes)."""

    def __init__(self, client: "RemoteEngine"):
        self._client = client

    def exists(self, f: RelationshipFilter) -> bool:
        return self._client._call("exists", filter=asdict(f))


class RemoteEngine:
    """Synchronous client with the Engine surface the proxy consumes.
    Thread-safe: a small connection pool lets concurrent request handlers
    (asyncio.to_thread workers) issue queries in parallel."""

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 timeout: float = 300.0, connect_timeout: float = 10.0,
                 pool_size: int = 8):
        self.host = host
        self.port = port
        self.token = token
        # response wait: generous — the first query after a snapshot
        # refresh pays an XLA compile measured in tens of seconds at the
        # 10M-relationship scale, and a timed-out-but-completing server op
        # would otherwise be retried against a still-busy server
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self._pool: list[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = pool_size
        self.store = _StoreShim(self)

    # -- transport ----------------------------------------------------------

    def _connect(self) -> socket.socket:
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.connect_timeout)
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _acquire(self) -> tuple[socket.socket, bool]:
        """(live connection, fresh?): pooled sockets are liveness-probed
        first, so a stale one (engine host restarted, peer FIN pending) is
        replaced BEFORE any request bytes are written — retrying after a
        send could double-apply a write the server already processed.
        ``fresh`` tells the caller the server hasn't authenticated this
        connection yet (the pre-auth frame cap applies)."""
        while True:
            with self._pool_lock:
                if not self._pool:
                    break
                s = self._pool.pop()
            try:
                s.setblocking(False)
                try:
                    probe = s.recv(1)
                    alive = False  # b'' (FIN) or stray data: discard
                except (BlockingIOError, InterruptedError):
                    alive = True
                    probe = None
                if alive:
                    s.settimeout(self.timeout)
                    return s, False
                del probe
            except OSError:
                pass
            s.close()
        return self._connect(), True

    def _release(self, s: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(s)
                return
        s.close()

    def close(self) -> None:
        with self._pool_lock:
            for s in self._pool:
                s.close()
            self._pool.clear()

    def _call(self, op: str, **args):
        msg = {"op": op, **args}
        if self.token:
            msg["token"] = self.token
        payload = _pack(msg)
        s, fresh = self._acquire()
        try:
            if fresh and self.token and len(payload) > MAX_FRAME_PREAUTH:
                # the server caps pre-auth frames; upgrade a fresh
                # connection with a cheap authenticated ping before the
                # big frame so bulk first-requests aren't dropped
                ping = self._round_trip(
                    s, _pack({"op": "revision", "token": self.token}))
                if not ping.get("ok"):
                    raise _ERROR_KINDS.get(
                        ping.get("kind", "internal"),
                        RemoteEngineError)(ping.get("error", ""))
            # no retry once bytes are on the wire: the server may have
            # processed the op even if the connection then died, and
            # replaying a write would double-apply it (staleness is
            # handled by the pre-send liveness probe in _acquire)
            resp = self._round_trip(s, payload)
        except Exception:
            s.close()
            raise
        self._release(s)
        if resp.get("ok"):
            return resp.get("result")
        kind = resp.get("kind", "internal")
        err = resp.get("error", "")
        raise _ERROR_KINDS.get(kind, RemoteEngineError)(err)

    def _round_trip(self, s: socket.socket, payload: bytes) -> dict:
        s.sendall(payload)
        return self._read_response(s)

    def _read_response(self, s: socket.socket) -> dict:
        header = self._recv_exact(s, 4)
        (n,) = struct.unpack(">I", header)
        if n > MAX_FRAME:
            raise RemoteEngineError(f"frame of {n} bytes exceeds limit")
        return json.loads(self._recv_exact(s, n))

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionResetError("engine connection closed")
            buf.extend(chunk)
        return bytes(buf)

    # -- engine surface ------------------------------------------------------

    def check(self, item: CheckItem, now: Optional[float] = None) -> bool:
        return self.check_bulk([item], now=now)[0]

    def check_bulk(self, items: list, now: Optional[float] = None) -> list:
        return self._call(
            "check_bulk", now=now,
            items=[[it.resource_type, it.resource_id, it.permission,
                    it.subject_type, it.subject_id, it.subject_relation]
                   for it in items])

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None) -> list:
        return self._call(
            "lookup_resources", resource_type=resource_type,
            permission=permission, subject_type=subject_type,
            subject_id=subject_id, subject_relation=subject_relation,
            now=now)

    def write_relationships(self, ops: list,
                            preconditions: list = ()) -> int:
        return self._call(
            "write_relationships",
            ops=[{"op": o.op, "rel": _rel_to_dict(o.rel)} for o in ops],
            preconditions=[{"filter": asdict(p.filter),
                            "must_exist": p.must_exist}
                           for p in preconditions])

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list = ()) -> int:
        return self._call(
            "delete_relationships", filter=asdict(f),
            preconditions=[{"filter": asdict(p.filter),
                            "must_exist": p.must_exist}
                           for p in preconditions])

    def read_relationships(self, f: RelationshipFilter):
        return [_rel_from_dict(d)
                for d in self._call("read_relationships", filter=asdict(f))]

    def watch_since(self, revision: int) -> list:
        return [
            WatchEvent(d["revision"], d["operation"],
                       _rel_from_dict(d["rel"]))
            for d in self._call("watch_since", revision=revision)
        ]

    def watch_gate(self, resource_type: str, name: str
                   ) -> tuple[Optional[frozenset], bool]:
        """Schema-derived recompute gate for watches, fetched from the
        engine host (which owns the schema). (None, True) against an
        older host that lacks the op — callers then recompute
        unconditionally and keep the expiry tick (the safe direction)."""
        try:
            r = self._call("watch_gate", resource_type=resource_type,
                           name=name)
            return frozenset(r["types"]), bool(r["use_expiration"])
        except RemoteEngineError:
            return None, True

    @property
    def revision(self) -> int:
        return self._call("revision")


def main(argv=None) -> int:
    """Standalone engine host: ``python -m
    spicedb_kubeapi_proxy_tpu.engine.remote --bootstrap schema.yaml
    --bind-port 50051`` — the TPU-owning process proxies connect to."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(prog="sdbkp-engine",
                                 description="TPU engine host")
    ap.add_argument("--bootstrap", action="append", default=[],
                    help="schema/relationships bootstrap YAML (repeatable)")
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--bind-port", type=int, default=50051)
    ap.add_argument("--token", help="shared bearer token")
    ap.add_argument("--snapshot-path",
                    help="relationship-store snapshot: loaded at boot if "
                         "present, saved on graceful shutdown")
    ap.add_argument("--engine-mesh",
                    help="device mesh for this host's chips: 'auto' or "
                         "'data=D,graph=G' (the engine host owns the mesh; "
                         "proxies connect with tcp://)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    mesh = None
    if args.engine_mesh:
        from ..parallel import make_mesh
        from ..parallel.mesh import parse_mesh_spec

        try:
            mesh = make_mesh(**parse_mesh_spec(args.engine_mesh))
        except ValueError as e:  # MeshSpecError or axis/device mismatch
            ap.error(str(e))
        log.info("engine mesh: %s", dict(mesh.shape))
    bootstrap = "\n---\n".join(open(f).read() for f in args.bootstrap) or None
    engine = Engine(bootstrap=bootstrap, mesh=mesh)
    if engine.load_snapshot_if_exists(args.snapshot_path):
        log.info("loaded snapshot %s (revision %d)", args.snapshot_path,
                 engine.revision)
    server = EngineServer(engine, args.bind_host, args.bind_port,
                          token=args.token)

    async def serve():
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await server.start()
        await stop.wait()
        await server.stop()
        if args.snapshot_path:
            engine.save_snapshot(args.snapshot_path)
            log.info("saved snapshot to %s", args.snapshot_path)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
