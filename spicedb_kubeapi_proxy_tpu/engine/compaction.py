"""Background compaction of the delta overlay into a fresh compiled base.

The write path appends into a fixed-capacity device-resident overlay
(ops/reachability.py ``incremental_update``): O(write) per mutation, but
occupancy only ever grows — queries drag the whole overlay segment
through every fixpoint phase, and a full overlay used to mean a
*synchronous* full recompile stalling the next fully-consistent read.
This module is the other half of the design (ROADMAP item 3, Samyama's
incremental view maintenance): a background **compactor** thread folds
the accumulated tail into a fresh double-buffered CSR base
(``compile_graph`` off the write path, the old base keeps serving),
replays whatever landed during the fold, and swaps the engine's compiled
graph atomically at a recorded revision. The swap preserves the
revision, so decision-cache keys — ``(kind, revision, query)`` — remain
exactly valid across it: compaction is semantically a no-op.

Overflow becomes **back-pressure** instead of a stall: when the overlay
cannot absorb a write, :class:`OverlayBackpressure` (an
:class:`~..admission.AdmissionRejected` subclass) sheds it BEFORE any
store mutation with a bounded ``Retry-After`` sized from the compactor's
recent fold times. The proxy middleware's fail-closed 503 path and the
engine host's ``kind='admission'`` wire frame both apply unchanged, so
client breakers stay closed and polite writers simply retry after the
fold.

Threshold semantics mirror the WAL checkpointer
(persistence/snapshot.py): ``notify`` is cheap and called on every
overlay advance; crossing ``threshold`` (fraction of capacity, overlay
slots or dead-ledger rows) wakes the worker.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from ..admission import AdmissionRejected
from ..utils.metrics import metrics

log = logging.getLogger("sdbkp.engine.compaction")

DEFAULT_COMPACT_THRESHOLD = 0.75

# Retry-After bounds for overlay-full sheds: never below the fold's
# scheduling granularity, never an unbounded "come back whenever"
MIN_RETRY_AFTER = 0.05
MAX_RETRY_AFTER = 5.0

# conservative slot-space edges one write record can expand to (direct +
# userset + arrow terms) — the headroom margin the shed check reserves
EDGES_PER_RECORD = 4


def validate_overlay_config(delta_capacity: int,
                            compact_threshold: float) -> None:
    """Shared flag-bounds check for ``--delta-capacity`` /
    ``--compact-threshold`` — ONE owner for the proxy options and the
    engine-host CLI (the admission validate_config pattern). Raises
    ``ValueError`` with a flag-named message."""
    if delta_capacity < 64:
        raise ValueError("delta-capacity must be >= 64 (the overlay "
                         "floor; it is part of the jit signature)")
    if not 0.0 <= compact_threshold <= 1.0:
        raise ValueError("compact-threshold must be in [0, 1] "
                         "(fraction of overlay capacity; 0 disables "
                         "background compaction)")


class OverlayBackpressure(AdmissionRejected):
    """The delta overlay cannot absorb the write and a compaction is in
    flight: shed BEFORE any store mutation, with a bounded Retry-After.
    Retrying is always safe — nothing was journaled, replicated, or
    applied."""

    def __init__(self, retry_after: float, occupancy: int, capacity: int,
                 what: str = "overlay slots"):
        super().__init__(
            "write",
            f"delta {what} full ({occupancy}/{capacity}); "
            "compaction in progress — retry after the fold",
            retry_after=retry_after,
            dependency="engine-compaction")
        self.occupancy = occupancy
        self.capacity = capacity
        self.what = what


class Compactor:
    """Threshold-triggered background overlay folds + write back-pressure.

    Owned by an :class:`~.engine.Engine` (``enable_compaction``). The
    worker thread is the ONLY caller of ``compile_graph`` once enabled —
    the serving path's fallback recompile still exists for correctness
    (layout growth, stratification inversions) but steady-state churn
    never reaches it: headroom sheds writes before the overlay can
    overflow."""

    def __init__(self, engine, threshold: float = DEFAULT_COMPACT_THRESHOLD):
        if not 0.0 < threshold <= 1.0:
            raise ValueError(
                f"compact threshold must be in (0, 1], got {threshold}")
        self.engine = engine
        self.threshold = float(threshold)
        self._cond = threading.Condition()
        self._pending = False
        # tiered-storage placement (storage/tiers.py): the worker doubles
        # as the placement engine — it decays access recency, demotes
        # blocks that went cold, and re-materializes pinned overlay
        # blocks, all off the serving path (reachability.tier_maintain)
        self._place_pending = False
        self._notify_count = 0
        self._closed = False
        # recent fold wall times, feeding the Retry-After estimate
        self._durations: deque = deque(maxlen=8)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-compactor")
        self._thread.start()

    # -- triggers ------------------------------------------------------------

    def notify(self, cg) -> None:
        """Cheap occupancy check, called with every advanced graph (the
        engine's incremental path and the write headroom check)."""
        if cg is None or cg.delta_pos is None or not cg.delta_cap:
            return
        if getattr(cg, "tier", None) is not None:
            # placement rides the same cheap per-advance hook: every
            # PLACE_EVERY advanced graphs, sweep residency once
            self._notify_count += 1
            if self._notify_count % self.PLACE_EVERY == 0:
                self.request_placement()
        if (cg.n_delta >= self.threshold * cg.delta_cap
                or cg.n_dead >= self.threshold * len(cg.dead_buf)):
            self.request()

    def request(self) -> None:
        """Ask for an async fold (idempotent while one is queued)."""
        with self._cond:
            if self._closed:
                return
            self._pending = True
            self._cond.notify()

    # advanced-graph notifies between placement sweeps; sweeps are cheap
    # (bookkeeping + at most a few block materializations) but need not
    # run per write
    PLACE_EVERY = 64

    def request_placement(self) -> None:
        """Ask for an async tier placement sweep (idempotent)."""
        with self._cond:
            if self._closed:
                return
            self._place_pending = True
            self._cond.notify()

    def retry_after(self) -> float:
        """Bounded shed hint: the median recent fold time — how long a
        polite writer should wait for overlay headroom to reappear."""
        if self._durations:
            d = sorted(self._durations)[len(self._durations) // 2]
        else:
            d = 0.2  # no fold observed yet: a compile-sized guess
        return min(max(d, MIN_RETRY_AFTER), MAX_RETRY_AFTER)

    def check_headroom(self, cg, n_records: int) -> None:
        """Write-path back-pressure: raise :class:`OverlayBackpressure`
        when the current overlay cannot absorb ``n_records`` more write
        records (conservatively ``EDGES_PER_RECORD`` slots each), and
        kick the worker once occupancy crosses the threshold. Called
        BEFORE the store mutation so a shed write leaves no trace."""
        if cg is None or cg.delta_pos is None or not cg.delta_cap:
            return
        need = EDGES_PER_RECORD * max(int(n_records), 1)
        slots_full = cg.n_delta + need > cg.delta_cap
        ledger_full = cg.n_dead + need > len(cg.dead_buf)
        if (slots_full or ledger_full
                or cg.n_delta + need > self.threshold * cg.delta_cap):
            self.request()
        if slots_full or ledger_full:
            metrics.counter("engine_overlay_backpressure_total").inc()
            # name the binding resource: a delete-heavy churn exhausts
            # the dead ledger while slot occupancy stays low, and the
            # operator's sizing fix is the same --delta-capacity either way
            if slots_full:
                raise OverlayBackpressure(self.retry_after(),
                                          cg.n_delta, cg.delta_cap)
            raise OverlayBackpressure(self.retry_after(),
                                      cg.n_dead, len(cg.dead_buf),
                                      what="dead-ledger rows")

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not (self._pending or self._place_pending) \
                        and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                do_fold, self._pending = self._pending, False
                do_place, self._place_pending = self._place_pending, False
            if do_place and not do_fold:
                # a fold supersedes placement: it rebuilds the graph —
                # and with it a fresh, unpinned TierStore
                from ..ops.reachability import tier_maintain

                try:
                    tier_maintain(self.engine._compiled)
                except Exception:
                    log.exception("tier placement sweep failed "
                                  "(will retry on next cadence)")
                continue
            try:
                self.compact()
            except Exception:
                log.exception("compaction failed (will retry on next "
                              "threshold crossing)")

    def compact(self) -> bool:
        """One synchronous fold: compile a fresh base off the write path
        (double-buffered — the current graph keeps serving), replay the
        records that landed during the compile, and swap atomically.
        Returns True when the swap published. Also the direct entry point
        for tests and graceful drains."""
        e = self.engine
        t0 = time.perf_counter()
        fresh = e._compile_fresh()
        with e._lock:
            cur = e._compiled
            if cur is not None and cur.revision > fresh.revision:
                # writes landed during the fold: bring the fresh base
                # current with one small incremental replay (bounded —
                # headroom shedding caps how much can accumulate)
                fresh = e._replay_onto(fresh)
            if fresh is None or (cur is not None
                                 and cur.revision > fresh.revision):
                # could not catch up (bulk load / trimmed history raced
                # the fold): go again from a newer snapshot
                self.request()
                return False
            e._compiled = fresh
            e._publish_graph_gauges(fresh)
        cache = getattr(e, "_decision_cache", None)
        if cache is not None:
            # entries AT the swap revision stay valid (the swap preserves
            # the revision); entries below it can never be probed again —
            # retire them here, at fold cadence, instead of letting churn
            # fill the LRU with dead revisions
            cache.retire_below(fresh.revision)
        dur = time.perf_counter() - t0
        self._durations.append(dur)
        metrics.counter("engine_compactions_total").inc()
        metrics.histogram("engine_compaction_seconds").observe(dur)
        metrics.gauge("engine_delta_occupancy").set(fresh.n_delta)
        log.info("compacted overlay into base at revision %d in %.3fs",
                 fresh.revision, dur)
        return True

    def close(self, drain: bool = False) -> None:
        """Stop the worker; ``drain=True`` folds one last time first."""
        if drain:
            try:
                self.compact()
            except Exception:
                log.exception("final compaction failed")
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=60.0)
