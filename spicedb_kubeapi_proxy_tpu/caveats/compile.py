"""Caveat compiler: typed AST -> constant-folded flat op tape.

The tape is the unit the vectorized VM executes (:mod:`.vm`): a register
machine with one instruction stream ``(op, dst, a, b)`` int32 plus an
f64 immediate per instruction, evaluated for every caveated-tuple
instance in parallel. Registers hold (value f64[N], known bool[N]) pairs
— the ``known`` plane carries three-valued logic, so missing context
flows structurally instead of via NaN tricks.

Lists never enter registers: every membership test lowers to ``IN`` over
a list id whose per-element inclusive [lo, hi] ranges live in the
instance tables (CIDR allowlist elements span a range; equality elements
are points). A literal list is a constant list id; a ``list<T>`` param
is a per-instance one.

Constant folding runs before lowering (literal arithmetic, comparisons,
boolean identities), so ``1 + 2 < x`` costs one comparison at runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .ast import (
    ARITH_OPS,
    Bin,
    CaveatDef,
    CaveatError,
    CavExpr,
    Lit,
    StringInterner,
    Un,
    Var,
    ip_words,
    parse_cidr_range,
    parse_cidr_range_mapped,
)

# -- opcodes (shared with vm.py; order is the lax.switch branch table) ------
OP_CONST = 0  # dst <- imm (known everywhere)
OP_LOAD = 1  # dst <- ctx column a
OP_AND = 2
OP_OR = 3
OP_NOT = 4  # dst <- !a
OP_EQ = 5
OP_NE = 6
OP_LT = 7
OP_LE = 8
OP_GT = 9
OP_GE = 10
OP_ADD = 11
OP_SUB = 12
OP_MUL = 13
OP_DIV = 14
OP_IN = 15  # dst <- (a in list b)

N_OPCODES = 16

_CMP_OPS = {"==": OP_EQ, "!=": OP_NE, "<": OP_LT, "<=": OP_LE,
            ">": OP_GT, ">=": OP_GE}
_ARITH = {"+": OP_ADD, "-": OP_SUB, "*": OP_MUL, "/": OP_DIV}

_NUMERIC = {"int", "uint", "double", "timestamp", "duration", "ipaddress",
            "bool"}


@dataclass(frozen=True)
class ListSpec:
    """One list id: a compile-time constant (``ranges`` set) or a
    ``list<elem>`` parameter column (``param`` set)."""

    ranges: Optional[tuple] = None  # tuple[(lo, hi), ...] for constants
    param: Optional[int] = None  # param index for per-instance lists
    elem: str = "double"


@dataclass
class CaveatProgram:
    """One compiled caveat: the tape plus everything the instance tables
    and request encoder need to lay out context columns."""

    name: str
    params: tuple  # CaveatParam tuple; scalar params get ctx columns
    ops: np.ndarray  # int32 [T, 4] (op, dst, a, b)
    imm: np.ndarray  # float64 [T]
    n_regs: int
    out_reg: int
    lists: tuple  # tuple[ListSpec, ...]
    # scalar-param name -> BASE ctx column; list-param name -> list id
    scalar_col: dict = field(default_factory=dict)
    list_id: dict = field(default_factory=dict)
    # scalar-param name -> how many consecutive columns it occupies:
    # 1 for everything except ipaddress, which rides FOUR 32-bit word
    # columns (the 128-bit mapped space cannot live on the 2^40-exact
    # split planes; word-wise lexicographic checks can — IPv6 support)
    scalar_width: dict = field(default_factory=dict)
    uses_now: bool = False  # references the auto-injected `now` param
    time_arith: bool = False  # arithmetic over timestamps: verdict flip
    #                           times are not enumerable from contexts

    @property
    def n_scalars(self) -> int:
        return sum(self.scalar_width.get(n, 1) for n in self.scalar_col)

    def signature(self) -> tuple:
        """Static shape key: everything the traced VM bakes in."""
        return (len(self.ops), self.n_regs, self.out_reg,
                self.n_scalars, len(self.lists),
                tuple(s.param if s.param is not None else -1
                      for s in self.lists))


def _typeof(e: CavExpr, defn: CaveatDef) -> str:
    """Resolve a node's type name ('list' for lists)."""
    if isinstance(e, Lit):
        return e.type
    if isinstance(e, Var):
        p = defn.param(e.name)
        if p is None:
            raise CaveatError(
                f"caveat {defn.name!r}: unknown parameter {e.name!r}")
        return "list" if p.type.is_list else p.type.name
    if isinstance(e, Un):
        return "bool"
    assert isinstance(e, Bin)
    if e.op in ("&&", "||", "in") or e.op in _CMP_OPS:
        return "bool"
    return "double"  # arithmetic


def _fold(e: CavExpr, defn: CaveatDef) -> CavExpr:
    """Constant-fold literal subtrees (numeric arithmetic, comparisons,
    boolean identities). Division by literal zero is NOT folded — it
    stays a runtime no-verdict (missing context, fail closed)."""
    if isinstance(e, (Lit, Var)):
        return e
    if isinstance(e, Un):
        inner = _fold(e.operand, defn)
        if isinstance(inner, Lit) and inner.type == "bool":
            return Lit(not inner.value, "bool")
        return Un(e.op, inner)
    assert isinstance(e, Bin)
    left = _fold(e.left, defn)
    right = _fold(e.right, defn)
    if isinstance(left, Lit) and isinstance(right, Lit):
        if e.op in _ARITH and left.type == "double" \
                and right.type == "double":
            a, b = float(left.value), float(right.value)
            if e.op == "+":
                return Lit(a + b, "double")
            if e.op == "-":
                return Lit(a - b, "double")
            if e.op == "*":
                return Lit(a * b, "double")
            if b != 0:
                return Lit(a / b, "double")
        elif e.op in _CMP_OPS and left.type == right.type \
                and left.type in ("double", "bool"):
            a = float(left.value) if left.type == "double" \
                else float(bool(left.value))
            b = float(right.value) if right.type == "double" \
                else float(bool(right.value))
            val = {"==": a == b, "!=": a != b, "<": a < b,
                   "<=": a <= b, ">": a > b, ">=": a >= b}[e.op]
            return Lit(val, "bool")
    # boolean identities: true && x -> x, false || x -> x, etc.
    if e.op == "&&":
        for lit, other in ((left, right), (right, left)):
            if isinstance(lit, Lit) and lit.type == "bool":
                return other if lit.value else Lit(False, "bool")
    if e.op == "||":
        for lit, other in ((left, right), (right, left)):
            if isinstance(lit, Lit) and lit.type == "bool":
                return Lit(True, "bool") if lit.value else other
    return Bin(e.op, left, right)


def typecheck(defn: CaveatDef) -> None:
    """Validate a declaration compiles (schema-parse-time gate); raises
    :class:`CaveatError` on type or reference errors."""
    compile_caveat(defn, StringInterner())


def compile_caveat(defn: CaveatDef,
                   interner: StringInterner) -> CaveatProgram:
    """Lower one caveat declaration to its op tape. String literals (and
    constant-list string elements) are interned into ``interner`` so
    tuple/request context values interned against the same table compare
    by code."""
    expr = _fold(defn.expr, defn)

    scalar_col: dict = {}
    scalar_width: dict = {}
    list_ids: dict = {}
    lists: list[ListSpec] = []
    next_col = 0
    for p in defn.params:
        if p.type.is_list:
            continue
        scalar_col[p.name] = next_col
        w = 4 if p.type.name == "ipaddress" else 1
        scalar_width[p.name] = w
        next_col += w
    param_index = {p.name: i for i, p in enumerate(defn.params)}

    ops: list[tuple[int, int, int, int]] = []
    imm: list[float] = []
    n_regs = 0
    uses_now = False
    time_arith = False

    def emit(op: int, a: int = 0, b: int = 0, im: float = 0.0) -> int:
        nonlocal n_regs
        dst = n_regs
        n_regs += 1
        ops.append((op, dst, a, b))
        imm.append(im)
        return dst

    def list_of(e: CavExpr, left_type: str) -> int:
        """Resolve a membership right-hand side to a list id."""
        if isinstance(e, Lit) and e.type == "list":
            key = ("const", e.value, left_type)
            got = list_ids.get(key)
            if got is not None:
                return got
            ranges = []
            for item in e.value:
                if isinstance(item, str):
                    if left_type == "ipaddress":
                        ranges.append(parse_cidr_range(item))
                    else:
                        x = float(interner.intern(item))
                        ranges.append((x, x))
                elif isinstance(item, bool):
                    ranges.append((float(item), float(item)))
                else:
                    ranges.append((float(item), float(item)))
            lid = len(lists)
            lists.append(ListSpec(ranges=tuple(ranges), elem=left_type))
            list_ids[key] = lid
            return lid
        if isinstance(e, Var):
            p = defn.param(e.name)
            if p is None or not p.type.is_list:
                raise CaveatError(
                    f"caveat {defn.name!r}: 'in' right-hand side "
                    f"{e.name!r} is not a list parameter")
            key = ("param", e.name)
            got = list_ids.get(key)
            if got is not None:
                return got
            lid = len(lists)
            lists.append(ListSpec(param=param_index[e.name],
                                  elem=p.type.elem))
            list_ids[key] = lid
            return lid
        raise CaveatError(
            f"caveat {defn.name!r}: 'in' needs a list literal or a "
            "list parameter on the right")

    def check_comparable(a: str, b: str, op: str) -> None:
        if "list" in (a, b):
            raise CaveatError(
                f"caveat {defn.name!r}: a list may only appear on the "
                "right of 'in'")
        if a == "string" or b == "string":
            if a != b:
                raise CaveatError(
                    f"caveat {defn.name!r}: {op!r} between string and "
                    f"{b if a == 'string' else a}")
            if op not in ("==", "!="):
                raise CaveatError(
                    f"caveat {defn.name!r}: strings support only "
                    "==/!= (interned codes are unordered)")
        if ("ipaddress" in (a, b)) and a != b:
            # wide (4-word) values order only against each other; a
            # cross-type compare against a plain number would compare
            # one word against the whole address — reject loudly
            raise CaveatError(
                f"caveat {defn.name!r}: {op!r} between ipaddress and "
                f"{b if a == 'ipaddress' else a}")

    # -- wide (4-word) ipaddress lowering: a mapped 128-bit address is
    # -- four 32-bit word registers; compares expand lexicographically
    # -- over existing opcodes (Kleene unknowns flow through AND/OR)

    def lower_ip(e: CavExpr) -> tuple:
        if isinstance(e, Var):
            p = defn.param(e.name)
            if p is not None and not p.type.is_list \
                    and p.type.name == "ipaddress":
                base = scalar_col[e.name]
                return tuple(emit(OP_LOAD, a=base + k)
                             for k in range(4))
        raise CaveatError(
            f"caveat {defn.name!r}: expected an ipaddress parameter")

    def const_words(x: int) -> tuple:
        return tuple(emit(OP_CONST, im=float(w)) for w in ip_words(x))

    def wide_and(regs: list) -> int:
        acc = regs[0]
        for r in regs[1:]:
            acc = emit(OP_AND, a=acc, b=r)
        return acc

    def wide_cmp(aw: tuple, bw: tuple, op: str) -> int:
        eqs = [emit(OP_EQ, a=aw[k], b=bw[k]) for k in range(4)]
        if op == "==":
            return wide_and(eqs)
        if op == "!=":
            return emit(OP_NOT, a=wide_and(eqs))
        strict = OP_LT if op in ("<", "<=") else OP_GT
        acc = emit(_CMP_OPS[op], a=aw[3], b=bw[3])
        for k in (2, 1, 0):
            s = emit(strict, a=aw[k], b=bw[k])
            acc = emit(OP_OR, a=s, b=emit(OP_AND, a=eqs[k], b=acc))
        return acc

    def wide_range_hit(aw: tuple, lo: int, hi: int) -> int:
        ge = wide_cmp(aw, const_words(lo), ">=")
        le = wide_cmp(aw, const_words(hi), "<=")
        return emit(OP_AND, a=ge, b=le)

    def lower(e: CavExpr) -> int:
        nonlocal uses_now, time_arith
        if isinstance(e, Lit):
            if e.type == "string":
                return emit(OP_CONST, im=float(interner.intern(e.value)))
            if e.type == "bool":
                return emit(OP_CONST, im=1.0 if e.value else 0.0)
            if e.type == "list":
                raise CaveatError(
                    f"caveat {defn.name!r}: a list may only appear on "
                    "the right of 'in'")
            return emit(OP_CONST, im=float(e.value))
        if isinstance(e, Var):
            p = defn.param(e.name)
            if p is None:
                raise CaveatError(
                    f"caveat {defn.name!r}: unknown parameter {e.name!r}")
            if p.type.is_list:
                raise CaveatError(
                    f"caveat {defn.name!r}: list parameter {e.name!r} "
                    "may only appear on the right of 'in'")
            if p.type.name == "ipaddress":
                # wide values have no single-register form: they exist
                # only inside compares and 'in' (handled above by the
                # Bin branches) — a bare/boolean use is meaningless
                raise CaveatError(
                    f"caveat {defn.name!r}: ipaddress parameter "
                    f"{e.name!r} may only be compared or tested "
                    "with 'in'")
            if e.name == "now" and p.type.name == "timestamp":
                uses_now = True
            return emit(OP_LOAD, a=scalar_col[e.name])
        if isinstance(e, Un):
            return emit(OP_NOT, a=lower(e.operand))
        assert isinstance(e, Bin)
        if e.op == "&&":
            return emit(OP_AND, a=lower(e.left), b=lower(e.right))
        if e.op == "||":
            return emit(OP_OR, a=lower(e.left), b=lower(e.right))
        if e.op == "in":
            lt = _typeof(e.left, defn)
            if lt == "list":
                raise CaveatError(
                    f"caveat {defn.name!r}: the left of 'in' must be "
                    "a scalar")
            if lt == "ipaddress":
                aw = lower_ip(e.left)
                if isinstance(e.right, Lit) and e.right.type == "list":
                    # literal CIDR allowlist: inline word-wise range
                    # checks in the full mapped space — exact for BOTH
                    # families (never the uint32 list table)
                    hits = []
                    for item in e.right.value:
                        if not isinstance(item, str):
                            raise CaveatError(
                                f"caveat {defn.name!r}: ipaddress list "
                                f"elements must be address/CIDR "
                                f"strings, got {item!r}")
                        lo, hi = parse_cidr_range_mapped(item)
                        hits.append(wide_range_hit(aw, lo, hi))
                    if not hits:
                        return emit(OP_CONST, im=0.0)
                    acc = hits[0]
                    for h in hits[1:]:
                        acc = emit(OP_OR, a=acc, b=h)
                    return acc
                lid = list_of(e.right, lt)
                spec = lists[lid]
                if spec.elem != "ipaddress":
                    raise CaveatError(
                        f"caveat {defn.name!r}: ipaddress 'in' "
                        f"list<{spec.elem}> mismatch")
                # per-instance lists hold the legacy uint32 (v4) ranges
                # (the split planes cap at 2^40; a list with any v6
                # element stays UNKNOWN — encode_list). A non-v4-mapped
                # operand selects an OUT-OF-RANGE sentinel (2^33 +
                # low word, above every uint32 range) instead of its
                # low word, so OP_IN itself answers: a KNOWN list
                # yields a genuine miss (it provably holds no v6
                # elements), an UNKNOWN list stays UNKNOWN — an outer
                # `is4 && hit` would Kleene-collapse that to a KNOWN
                # False, which `!(ip in blocked)` flips into a grant
                z = emit(OP_CONST, im=0.0)
                ff = emit(OP_CONST, im=65535.0)
                is4 = wide_and([
                    emit(OP_EQ, a=aw[0], b=z),
                    emit(OP_EQ, a=aw[1], b=z),
                    emit(OP_EQ, a=aw[2], b=ff)])
                not4 = emit(OP_NOT, a=is4)
                big = emit(OP_CONST, im=float(1 << 33))
                off = emit(OP_MUL, a=not4, b=big)  # 0 or 2^33: exact
                sel = emit(OP_ADD, a=aw[3], b=off)
                return emit(OP_IN, a=sel, b=lid)
            lid = list_of(e.right, lt)
            spec = lists[lid]
            if spec.elem == "ipaddress":
                raise CaveatError(
                    f"caveat {defn.name!r}: {lt} 'in' "
                    "list<ipaddress> mismatch")
            if spec.elem != lt and not (
                    spec.elem in _NUMERIC and lt in _NUMERIC):
                raise CaveatError(
                    f"caveat {defn.name!r}: {lt} 'in' "
                    f"list<{spec.elem}> mismatch")
            return emit(OP_IN, a=lower(e.left), b=lid)
        lt, rt = _typeof(e.left, defn), _typeof(e.right, defn)
        if e.op in _CMP_OPS:
            check_comparable(lt, rt, e.op)
            if lt == rt == "ipaddress":
                return wide_cmp(lower_ip(e.left), lower_ip(e.right),
                                e.op)
            return emit(_CMP_OPS[e.op], a=lower(e.left),
                        b=lower(e.right))
        if e.op in ARITH_OPS:
            check_comparable(lt, rt, e.op)
            if lt == "string" or rt == "string":
                raise CaveatError(
                    f"caveat {defn.name!r}: arithmetic over strings")
            if "ipaddress" in (lt, rt):
                raise CaveatError(
                    f"caveat {defn.name!r}: arithmetic over IP "
                    "addresses is meaningless (wide values only "
                    "compare)")
            if "timestamp" in (lt, rt):
                # verdict flip instants are no longer enumerable from
                # the stored contexts; the engine must not cache
                time_arith = True
            return emit(_ARITH[e.op], a=lower(e.left), b=lower(e.right))
        raise CaveatError(f"unknown operator {e.op!r}")

    if isinstance(expr, Lit) and expr.type == "bool":
        out = emit(OP_CONST, im=1.0 if expr.value else 0.0)
    else:
        if _typeof(expr, defn) != "bool":
            raise CaveatError(
                f"caveat {defn.name!r}: body must be boolean, got "
                f"{_typeof(expr, defn)}")
        out = lower(expr)

    return CaveatProgram(
        name=defn.name,
        params=defn.params,
        ops=np.asarray(ops, dtype=np.int32).reshape(-1, 4),
        imm=np.asarray(imm, dtype=np.float64),
        n_regs=n_regs,
        out_reg=out,
        lists=tuple(lists),
        scalar_col=scalar_col,
        scalar_width=scalar_width,
        list_id={k[1]: v for k, v in list_ids.items()
                 if k[0] == "param"},
        uses_now=uses_now,
        time_arith=time_arith,
    )
