"""The vectorized caveat VM and its host-side instance tables.

Execution model
---------------
One caveat = one op tape (:mod:`.compile`); one *instance* = one distinct
``(caveat, context)`` pair carried by at least one live tuple. The VM
evaluates every instance of every caveat in ONE traced pass per device
dispatch (``lax.scan`` over the tape, ``lax.switch`` over opcodes), and
the reachability fixpoint consumes the result as a per-instance validity
row — edge activation becomes ``(exp > now) & cav_ok[edge_row]``, fused
into the same jit as the fixpoint (zero per-tuple host round trips).

Value representation
--------------------
TPUs run without x64, so a single f32 plane cannot hold IPv4 addresses,
interned string codes past 2^24, or unix timestamps exactly. Every
scalar therefore rides TWO f32 planes::

    ext = floor(v / 2**16)        val = v - ext * 2**16

a monotone split that is exact for all integers |v| < 2^40 (both planes
stay under 2^24): comparisons are lexicographic on (ext, val), equality
is plane-wise, and ``in`` is a lexicographic [lo, hi] range check per
list element — which makes CIDR allowlists ordinary interval tests.
Additions renormalize the carry; mul/div recombine into one f32 (wide
products lose low bits, but arithmetic on wide domains — IPs — is
meaningless anyway and timestamp arithmetic already disables caching).

Three-valued logic rides an explicit ``known`` plane (never NaN):
missing context flows structurally, ``&&``/``||`` are Kleene, and the
top-level UNKNOWN is the missing-context verdict the engine fails
closed and counts.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .ast import (
    CaveatError,
    StringInterner,
    UnencodableListError,
    encode_list,
    encode_scalar,
    ip_words,
    parse_ip_mapped,
)
from .compile import (
    CaveatProgram,
    N_OPCODES,
    OP_ADD,
    OP_AND,
    OP_CONST,
    OP_DIV,
    OP_EQ,
    OP_GE,
    OP_GT,
    OP_IN,
    OP_LE,
    OP_LOAD,
    OP_LT,
    OP_MUL,
    OP_NE,
    OP_NOT,
    OP_OR,
    OP_SUB,
    compile_caveat,
)

SPLIT = 65536.0  # the plane radix (2^16)

#: the auto-injected request-context key: a ``now timestamp`` parameter
#: is filled with the dispatch clock unless the caller supplied it
NOW_PARAM = "now"


def split_planes(v) -> tuple[np.ndarray, np.ndarray]:
    """f64 value(s) -> (ext, val) f32 planes (monotone, integer-exact
    to 2^40)."""
    v = np.asarray(v, dtype=np.float64)
    ext = np.floor(v / SPLIT)
    return ext.astype(np.float32), (v - ext * SPLIT).astype(np.float32)


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass(frozen=True)
class CavMeta:
    """Static shape of one caveat's VM invocation — baked into the jit
    signature (RunMeta.caveats), shared across revisions. ``P``/``L``
    are the ALLOCATED context/list rows (>= 1 so every lax.switch
    branch traces against real shapes even when unused)."""

    name: str
    T: int  # tape length
    n_regs: int
    out_reg: int
    P: int  # scalar context columns (allocated)
    L: int  # list ids (allocated)
    K: int  # list capacity (elements per list)
    n_pad: int  # instance rows (padded)
    n_real: int  # real instances at compile time
    row_off: int  # global cav_ok row of this caveat's instance 0


@dataclass
class _CavHost:
    """One caveat's host-side arrays (shared across incremental
    descendants; mutated only under the graph's host lock)."""

    program: CaveatProgram
    ctx_e: np.ndarray  # f32 [P, n_pad]
    ctx_v: np.ndarray
    ctx_k: np.ndarray  # bool [P, n_pad]
    lo_e: np.ndarray  # f32 [L, K, n_pad]
    lo_v: np.ndarray
    hi_e: np.ndarray
    hi_v: np.ndarray
    list_k: np.ndarray  # bool [L, n_pad]
    real: np.ndarray  # bool [n_pad] — 1 = a live instance row


def _dict_timestamps(prog: CaveatProgram, ctx: dict) -> list[float]:
    """Timestamp values a context dict supplies for a program's declared
    timestamp parameters (scalars and list elements) — the verdict-flip
    instants a `now` comparison can cross."""
    from .ast import parse_timestamp

    out: list[float] = []
    for p in prog.params:
        if p.name == NOW_PARAM or p.name not in ctx:
            continue
        try:
            if p.type.is_list and p.type.elem == "timestamp":
                vals = ctx[p.name]
                if isinstance(vals, list):
                    out.extend(parse_timestamp(v) for v in vals)
            elif not p.type.is_list and p.type.name == "timestamp":
                out.append(parse_timestamp(ctx[p.name]))
        except CaveatError:
            continue
    return out


def _ctx_timestamps(prog: CaveatProgram, ctx_json: str) -> list[float]:
    try:
        ctx = json.loads(ctx_json) if ctx_json else {}
    except ValueError:
        return []
    return _dict_timestamps(prog, ctx) if isinstance(ctx, dict) else []


def _encode_instance_cols(meta: CavMeta, prog: CaveatProgram,
                          interner: StringInterner, ctx_json: str):
    """Encode one stored context JSON into one instance row's columns
    (strict: tuple contexts intern new strings)."""
    ctx = json.loads(ctx_json) if ctx_json else {}
    if not isinstance(ctx, dict):
        raise CaveatError(f"caveat context must be an object: {ctx_json!r}")
    sce = np.zeros(meta.P, dtype=np.float32)
    scv = np.zeros(meta.P, dtype=np.float32)
    sck = np.zeros(meta.P, dtype=bool)
    lle = np.zeros((meta.L, meta.K), dtype=np.float32)
    llv = np.zeros((meta.L, meta.K), dtype=np.float32)
    lhe = np.full((meta.L, meta.K), -1.0, dtype=np.float32)
    lhv = np.zeros((meta.L, meta.K), dtype=np.float32)
    lk = np.zeros(meta.L, dtype=bool)
    # constant lists are "known" with their literal ranges on every row
    for lid, spec in enumerate(prog.lists):
        if spec.ranges is None:
            continue
        if len(spec.ranges) > meta.K:
            raise CaveatError(
                f"caveat {meta.name!r}: constant list exceeds capacity")
        for j, (lo, hi) in enumerate(spec.ranges):
            lle[lid, j], llv[lid, j] = split_planes(lo)
            lhe[lid, j], lhv[lid, j] = split_planes(hi)
        lk[lid] = True
    for p in prog.params:
        if p.name not in ctx:
            continue
        if p.type.is_list:
            lid = prog.list_id.get(p.name)
            if lid is None:
                continue  # declared but unused in the expression
            try:
                ranges = encode_list(ctx[p.name], p.type.elem, interner,
                                     strict=True)
            except UnencodableListError:
                continue  # list stays UNKNOWN: fail closed either way
            if len(ranges) > meta.K:
                raise CaveatError(
                    f"caveat {meta.name!r}: list {p.name!r} exceeds "
                    f"row capacity {meta.K}")
            for j, (lo, hi) in enumerate(ranges):
                lle[lid, j], llv[lid, j] = split_planes(lo)
                lhe[lid, j], lhv[lid, j] = split_planes(hi)
            lk[lid] = True
        else:
            col = prog.scalar_col.get(p.name)
            if col is None:
                continue
            if p.type.name == "ipaddress":
                # wide value: four 32-bit words across consecutive
                # columns — exact for BOTH families (IPv6 support)
                for k, w in enumerate(
                        ip_words(parse_ip_mapped(ctx[p.name]))):
                    sce[col + k], scv[col + k] = split_planes(float(w))
                    sck[col + k] = True
                continue
            x = encode_scalar(ctx[p.name], p.type.name, interner,
                              strict=True)
            sce[col], scv[col] = split_planes(x)
            sck[col] = True
    return sce, scv, sck, lle, llv, lhe, lhv, lk


@dataclass
class CompiledCaveats:
    """Every caveat instance in one compiled graph, device-ready.

    Shared (like the delta overlay) by every incremental descendant of
    one compiled base: instance appends mutate the host arrays in place
    under the graph's host lock and publish functional device updates
    into the new revision's view only.
    """

    metas: tuple  # tuple[CavMeta, ...]
    hosts: list  # list[_CavHost] aligned with metas
    interner: StringInterner
    n_rows: int  # 1 + sum(n_pad): row 0 = uncaveated/always-valid
    inst_row: np.ndarray  # store caveat-instance id -> global row (0=none)
    key_row: dict  # (name, ctx_json) -> global row
    n_inst: int  # live instance rows (incl. appended)
    time_bounds: np.ndarray  # sorted unique unix seconds (verdict flips)
    time_exact: bool  # False: flips not enumerable (timestamp arith)
    any_now: bool  # some program reads the auto-injected clock

    @property
    def n_instances(self) -> int:
        return self.n_inst

    def param_names(self) -> frozenset:
        """Every parameter name any compiled caveat declares — the ONLY
        request-context keys that can influence a verdict."""
        got = getattr(self, "_param_names", None)
        if got is None:
            got = frozenset(
                p.name for h in self.hosts for p in h.program.params)
            self._param_names = got
        return got

    def relevant_context(self, context: Optional[dict]
                         ) -> Optional[dict]:
        """The subset of a request context the compiled caveats can
        actually read. Decision-cache digests hash ONLY this — fields
        no caveat declares (the middleware's name/verb/resource/...)
        would otherwise fragment the cache per request while provably
        unable to change any verdict."""
        if not context:
            return None
        names = self.param_names()
        out = {k: v for k, v in context.items() if k in names}
        return out or None

    def request_ts(self, context: Optional[dict]) -> list:
        """Request-supplied verdict-flip timestamps (cache-deadline
        input) — a cheap scan, not a full array encode."""
        if not context or not self.any_now:
            return []
        out: list = []
        for h in self.hosts:
            if h.program.uses_now:
                out.extend(_dict_timestamps(h.program, context))
        return out

    def signature(self) -> tuple:
        return tuple(
            (m.name, m.T, m.n_regs, m.out_reg, m.P, m.L, m.K, m.n_pad,
             m.n_real, m.row_off) for m in self.metas)

    # -- request-context encoding -------------------------------------------

    def encode_request(self, context: Optional[dict], now: float
                       ) -> tuple[tuple, list]:
        """(per-caveat request arrays pytree, request timestamp values).

        Unknown context keys are ignored (SpiceDB passes extra context
        through); malformed values for a declared parameter leave the
        parameter UNKNOWN — missing context, which fails closed — rather
        than erroring the whole dispatch."""
        context = context or {}
        out = []
        req_ts: list[float] = []
        # ONE scratch per call: unseen request strings get distinct
        # negative codes (consistent across this call's caveats; a
        # shared -1 sentinel would make any two unseen strings compare
        # equal — fail open), and nothing accumulates on the shared
        # table under adversarial request values
        scratch = self.interner.scratch()
        for m, h in zip(self.metas, self.hosts):
            prog = h.program
            rce = np.zeros(m.P, dtype=np.float32)
            rcv = np.zeros(m.P, dtype=np.float32)
            rck = np.zeros(m.P, dtype=bool)
            rloe = np.zeros((m.L, m.K), dtype=np.float32)
            rlov = np.zeros((m.L, m.K), dtype=np.float32)
            rhie = np.full((m.L, m.K), -1.0, dtype=np.float32)
            rhiv = np.zeros((m.L, m.K), dtype=np.float32)
            rlk = np.zeros(m.L, dtype=bool)
            for p in prog.params:
                if p.type.is_list:
                    lid = prog.list_id.get(p.name)
                    if lid is None or p.name not in context:
                        continue
                    try:
                        ranges = encode_list(context[p.name], p.type.elem,
                                             scratch, strict=False)
                    except CaveatError:
                        continue
                    if len(ranges) > m.K:
                        # oversized request list: the parameter stays
                        # UNKNOWN (fails closed) — counted so operators
                        # can tell capacity overflow from genuinely
                        # absent context and raise the tuple-side lists
                        # (K sizes from them) or trim the request's
                        from ..utils.metrics import metrics

                        metrics.counter(
                            "engine_caveat_request_list_overflow_total"
                        ).inc()
                        continue
                    for j, (lo, hi) in enumerate(ranges):
                        rloe[lid, j], rlov[lid, j] = split_planes(lo)
                        rhie[lid, j], rhiv[lid, j] = split_planes(hi)
                        if p.type.elem == "timestamp":
                            req_ts.extend((lo, hi))
                    rlk[lid] = True
                    continue
                col = prog.scalar_col.get(p.name)
                if col is None:
                    continue
                if p.type.name == "ipaddress":
                    if p.name not in context:
                        continue
                    try:
                        words = ip_words(
                            parse_ip_mapped(context[p.name]))
                    except CaveatError:
                        continue  # malformed -> UNKNOWN (fails closed)
                    for k, w in enumerate(words):
                        rce[col + k], rcv[col + k] = split_planes(
                            float(w))
                        rck[col + k] = True
                    continue
                if p.name in context:
                    try:
                        x = encode_scalar(context[p.name], p.type.name,
                                          scratch, strict=False)
                    except CaveatError:
                        continue
                elif p.name == NOW_PARAM and p.type.name == "timestamp":
                    x = float(now)
                else:
                    continue
                rce[col], rcv[col] = split_planes(x)
                rck[col] = True
                if p.type.name == "timestamp" and p.name != NOW_PARAM:
                    req_ts.append(x)
            out.append({"ce": rce, "cv": rcv, "ck": rck,
                        "loe": rloe, "lov": rlov, "hie": rhie,
                        "hiv": rhiv, "lk": rlk})
        return tuple(out), req_ts

    def next_time_bound(self, now: float, extra_ts=()) -> float:
        """Earliest verdict-flip instant strictly after ``now`` — the
        caveat analog of the store's expiration watermark, joined into
        decision-cache deadlines. ``now`` itself when flips are not
        enumerable (timestamp arithmetic): entries are born dead, i.e.
        contexted queries effectively uncached."""
        if not self.metas or not self.any_now:
            return float("inf")
        if not self.time_exact:
            return now
        bounds = self.time_bounds
        if extra_ts:
            bounds = np.union1d(bounds, np.asarray(list(extra_ts),
                                                   dtype=np.float64))
        i = int(np.searchsorted(bounds, now, side="right"))
        return float(bounds[i]) if i < len(bounds) else float("inf")

    # -- device upload -------------------------------------------------------

    def device_static(self, sharding=None) -> tuple:
        """Per-caveat device arrays (called under the graph host guard;
        the result lives in CompiledGraph._device). ``sharding``: an
        optional placement for every array — the mesh backend passes a
        replicated ``NamedSharding(mesh, P())`` so the instance tables
        and VM tapes live identically on every device and the caveat
        pass runs inside the shard_map body with no cross-chip
        traffic."""
        if sharding is None:
            def put(a):
                return jnp.asarray(a)
        else:
            def put(a):
                return jax.device_put(np.asarray(a), sharding)
        out = []
        for h in self.hosts:
            ime, imv = split_planes(h.program.imm)
            out.append({
                "ops": put(h.program.ops),
                "ime": put(ime), "imv": put(imv),
                "ce": put(h.ctx_e), "cv": put(h.ctx_v),
                "ck": put(h.ctx_k),
                "loe": put(h.lo_e), "lov": put(h.lo_v),
                "hie": put(h.hi_e), "hiv": put(h.hi_v),
                "lk": put(h.list_k),
                "real": put(h.real),
            })
        return tuple(out)

    def applied_rows(self) -> tuple:
        """Per-caveat live instance-row counts — the append watermark a
        mesh view syncs its replicated tables against (spare rows are
        taken append-only per caveat, so ``[old, new)`` names exactly
        the columns to patch). Caller holds the graph host guard."""
        return tuple(int(h.real.sum()) for h in self.hosts)

    # -- incremental instance appends ---------------------------------------

    def lookup_row(self, name: str, ctx_json: str) -> Optional[int]:
        return self.key_row.get((name, ctx_json))

    def plan_append(self, name: str, ctx_json: str,
                    planned: dict) -> Optional[int]:
        """Reserve (in ``planned``, not yet applied) a free instance row
        for a new (caveat, context) pair; None when the caveat has no
        compiled tape, its row bucket is full, or the context cannot be
        encoded against the frozen layout — the caller falls back to a
        full recompile."""
        got = planned.get((name, ctx_json))
        if got is not None:
            return got[0]
        for ci, (m, h) in enumerate(zip(self.metas, self.hosts)):
            if m.name != name:
                continue
            used = int(h.real.sum()) + sum(
                1 for (n2, _), (_, ci2, _) in planned.items()
                if n2 == name and ci2 == ci)
            if used >= m.n_pad:
                return None
            try:
                cols = _encode_instance_cols(m, h.program, self.interner,
                                             ctx_json)
            except (CaveatError, ValueError):
                return None
            row = m.row_off + used
            planned[(name, ctx_json)] = (row, ci, (used, cols))
            return row
        return None  # caveat had no instances at compile: no tape

    def apply_appends(self, planned: dict) -> list:
        """Write planned instance rows into the shared host arrays
        (caller holds the graph host lock) and return
        ``[(c_idx, local_row, cols), ...]`` for the device-side
        functional updates."""
        out = []
        new_ts: list[float] = []
        for (name, ctx_json), (row, ci, (local, cols)) in planned.items():
            h = self.hosts[ci]
            sce, scv, sck, lle, llv, lhe, lhv, lk = cols
            h.ctx_e[:, local] = sce
            h.ctx_v[:, local] = scv
            h.ctx_k[:, local] = sck
            h.lo_e[:, :, local] = lle
            h.lo_v[:, :, local] = llv
            h.hi_e[:, :, local] = lhe
            h.hi_v[:, :, local] = lhv
            h.list_k[:, local] = lk
            h.real[local] = True
            self.key_row[(name, ctx_json)] = row
            self.n_inst += 1
            out.append((ci, local, cols))
            # verdict-flip watermark: a `now`-reading caveat's NEW
            # instance brings new flip instants — without extending the
            # bounds, a cached ALLOW filled before this append could
            # outlive the new tuple's window (stale grant past
            # revocation, exactly what the watermark exists to prevent)
            if h.program.uses_now:
                new_ts.extend(_ctx_timestamps(h.program, ctx_json))
        if new_ts:
            # replace, never mutate: readers (next_time_bound on cache
            # fills, off the engine lock) see either array atomically
            self.time_bounds = np.union1d(
                self.time_bounds,
                np.asarray([t for t in new_ts if np.isfinite(t)],
                           dtype=np.float64))
        return out


# ---------------------------------------------------------------------------
# Table construction (compile_graph time)
# ---------------------------------------------------------------------------


def build_caveat_table(caveat_defs: dict, inst_table: list,
                       used_ids) -> CompiledCaveats:
    """Compile every caveat with live instances and lay out the instance
    tables. ``inst_table`` is the store's append-only
    ``(name, ctx_json)`` list (index 0 reserved); ``used_ids`` the
    distinct nonzero instance ids among live tuples."""
    interner = StringInterner()
    by_name: dict[str, list[int]] = {}
    for iid in sorted(int(x) for x in used_ids):
        name = inst_table[iid][0]
        by_name.setdefault(name, []).append(iid)

    metas: list[CavMeta] = []
    hosts: list[_CavHost] = []
    inst_row = np.zeros(max(len(inst_table), 1), dtype=np.int64)
    key_row: dict = {}
    ts_bounds: list[float] = []
    time_exact = True
    any_now = False
    row_off = 1  # row 0 = uncaveated / always valid
    for name in sorted(by_name):
        defn = caveat_defs.get(name)
        if defn is None:
            raise CaveatError(
                f"tuples reference undeclared caveat {name!r}")
        prog = compile_caveat(defn, interner)
        ids = by_name[name]
        n_real = len(ids)
        n_pad = _bucket(n_real, 8)
        # list capacity: the longest tuple-context or constant list,
        # with bucket headroom so appended instances rarely force a
        # recompile. Floor 16: request-supplied lists (e.g. the
        # middleware's `groups`) have no tuple-side sizing signal, and
        # a floor of 4 would silently drop any 5-group caller to
        # missing context
        k_need = 1
        for spec in prog.lists:
            if spec.ranges is not None:
                k_need = max(k_need, len(spec.ranges))
        for iid in ids:
            try:
                ctx = json.loads(inst_table[iid][1] or "{}")
            except ValueError:
                ctx = {}
            if isinstance(ctx, dict):
                for p in prog.params:
                    if p.type.is_list \
                            and isinstance(ctx.get(p.name), list):
                        k_need = max(k_need, len(ctx[p.name]))
        meta = CavMeta(
            name=name, T=len(prog.ops), n_regs=prog.n_regs,
            out_reg=prog.out_reg, P=max(prog.n_scalars, 1),
            L=max(len(prog.lists), 1), K=_bucket(k_need, 16),
            n_pad=n_pad, n_real=n_real, row_off=row_off)
        host = _CavHost(
            program=prog,
            ctx_e=np.zeros((meta.P, n_pad), dtype=np.float32),
            ctx_v=np.zeros((meta.P, n_pad), dtype=np.float32),
            ctx_k=np.zeros((meta.P, n_pad), dtype=bool),
            lo_e=np.zeros((meta.L, meta.K, n_pad), dtype=np.float32),
            lo_v=np.zeros((meta.L, meta.K, n_pad), dtype=np.float32),
            hi_e=np.full((meta.L, meta.K, n_pad), -1.0, dtype=np.float32),
            hi_v=np.zeros((meta.L, meta.K, n_pad), dtype=np.float32),
            list_k=np.zeros((meta.L, n_pad), dtype=bool),
            real=np.zeros(n_pad, dtype=bool),
        )
        for local, iid in enumerate(ids):
            name_i, ctx_json = inst_table[iid]
            cols = _encode_instance_cols(meta, prog, interner, ctx_json)
            sce, scv, sck, lle, llv, lhe, lhv, lk = cols
            host.ctx_e[:, local] = sce
            host.ctx_v[:, local] = scv
            host.ctx_k[:, local] = sck
            host.lo_e[:, :, local] = lle
            host.lo_v[:, :, local] = llv
            host.hi_e[:, :, local] = lhe
            host.hi_v[:, :, local] = lhv
            host.list_k[:, local] = lk
            host.real[local] = True
            inst_row[iid] = row_off + local
            key_row[(name_i, ctx_json)] = row_off + local
        metas.append(meta)
        hosts.append(host)
        if prog.time_arith:
            time_exact = False
        if prog.uses_now:
            any_now = True
            # verdict-flip instants: every timestamp the stored contexts
            # (and constant tape immediates) can compare now against
            ts_bounds.extend(float(x) for x in prog.imm[
                prog.ops[:, 0] == OP_CONST].tolist())
            for iid in ids:
                ts_bounds.extend(
                    _ctx_timestamps(prog, inst_table[iid][1]))
        row_off += n_pad

    bounds = np.unique(np.asarray(
        [t for t in ts_bounds if np.isfinite(t)], dtype=np.float64)) \
        if ts_bounds else np.empty(0, dtype=np.float64)
    return CompiledCaveats(
        metas=tuple(metas), hosts=hosts, interner=interner,
        n_rows=row_off, inst_row=inst_row, key_row=key_row,
        n_inst=sum(m.n_real for m in metas),
        time_bounds=bounds, time_exact=time_exact, any_now=any_now)


# ---------------------------------------------------------------------------
# Traced evaluation (called from inside the reachability jit)
# ---------------------------------------------------------------------------


def _truthy(e, v):
    return (e != 0) | (v != 0)


def _vm_eval(meta: CavMeta, stat: dict, req: dict):
    """Evaluate one caveat's tape over its padded instance rows.
    Returns (allow uint8 [n_pad], missing bool [n_pad]) — allow is the
    known-true tri-state arm; missing is UNKNOWN."""
    N = meta.n_pad
    # merge: tuple context overrides request context (SpiceDB precedence)
    rce = jnp.broadcast_to(req["ce"][:, None], (meta.P, N))
    rcv = jnp.broadcast_to(req["cv"][:, None], (meta.P, N))
    rck = jnp.broadcast_to(req["ck"][:, None], (meta.P, N))
    ce = jnp.where(stat["ck"], stat["ce"], rce)
    cv = jnp.where(stat["ck"], stat["cv"], rcv)
    ck = stat["ck"] | rck
    tlk = stat["lk"]
    pick = tlk[:, None, :]  # [L, 1, N]
    shape = (meta.L, meta.K, N)
    loe = jnp.where(pick, stat["loe"],
                    jnp.broadcast_to(req["loe"][:, :, None], shape))
    lov = jnp.where(pick, stat["lov"],
                    jnp.broadcast_to(req["lov"][:, :, None], shape))
    hie = jnp.where(pick, stat["hie"],
                    jnp.broadcast_to(req["hie"][:, :, None], shape))
    hiv = jnp.where(pick, stat["hiv"],
                    jnp.broadcast_to(req["hiv"][:, :, None], shape))
    lk = tlk | jnp.broadcast_to(req["lk"][:, None], (meta.L, N))

    R = max(meta.n_regs, 1)
    regs_e = jnp.zeros((R, N), dtype=jnp.float32)
    regs_v = jnp.zeros((R, N), dtype=jnp.float32)
    regs_k = jnp.zeros((R, N), dtype=jnp.bool_)
    ones = jnp.ones(N, dtype=jnp.bool_)

    def step(carry, ins):
        re_, rv, rk = carry
        row, ime, imv = ins
        op, dst, a, b = row[0], row[1], row[2], row[3]
        ae = jnp.take(re_, a, axis=0)
        av = jnp.take(rv, a, axis=0)
        ak = jnp.take(rk, a, axis=0)
        be = jnp.take(re_, b, axis=0)
        bv = jnp.take(rv, b, axis=0)
        bk = jnp.take(rk, b, axis=0)
        at = ak & _truthy(ae, av)
        af = ak & ~_truthy(ae, av)
        bt = bk & _truthy(be, bv)
        bf = bk & ~_truthy(be, bv)
        kab = ak & bk

        def as_bool(val, known):
            return (jnp.zeros(N, jnp.float32),
                    val.astype(jnp.float32), known)

        def c_const():
            return (jnp.full(N, ime, jnp.float32),
                    jnp.full(N, imv, jnp.float32), ones)

        def c_load():
            return (jnp.take(ce, a, axis=0), jnp.take(cv, a, axis=0),
                    jnp.take(ck, a, axis=0))

        def c_and():
            return as_bool(at & bt, (af | bf) | (at & bt))

        def c_or():
            return as_bool(at | bt, (at | bt) | (af & bf))

        def c_not():
            return as_bool(af, ak)

        def c_eq():
            return as_bool((ae == be) & (av == bv), kab)

        def c_ne():
            return as_bool((ae != be) | (av != bv), kab)

        def c_lt():
            return as_bool((ae < be) | ((ae == be) & (av < bv)), kab)

        def c_le():
            return as_bool((ae < be) | ((ae == be) & (av <= bv)), kab)

        def c_gt():
            return as_bool((ae > be) | ((ae == be) & (av > bv)), kab)

        def c_ge():
            return as_bool((ae > be) | ((ae == be) & (av >= bv)), kab)

        def _renorm(e, v):
            carry_ = jnp.floor(v / SPLIT)
            return e + carry_, v - carry_ * SPLIT

        def c_add():
            e, v = _renorm(ae + be, av + bv)
            return e, v, kab

        def c_sub():
            e, v = _renorm(ae - be, av - bv)
            return e, v, kab

        def _combine(e, v):
            return e * jnp.float32(SPLIT) + v

        def c_mul():
            r = _combine(ae, av) * _combine(be, bv)
            e = jnp.floor(r / SPLIT)
            return e, r - e * SPLIT, kab

        def c_div():
            denom = _combine(be, bv)
            safe = jnp.where(denom == 0, jnp.float32(1), denom)
            r = _combine(ae, av) / safe
            e = jnp.floor(r / SPLIT)
            # division by zero: no verdict (missing context, fail closed)
            return e, r - e * SPLIT, kab & (denom != 0)

        def c_in():
            le = jnp.take(loe, b, axis=0)  # [K, N]
            lv = jnp.take(lov, b, axis=0)
            he = jnp.take(hie, b, axis=0)
            hv = jnp.take(hiv, b, axis=0)
            ge = (ae > le) | ((ae == le) & (av >= lv))
            lte = (ae < he) | ((ae == he) & (av <= hv))
            hit = jnp.any(ge & lte, axis=0)
            return as_bool(hit, ak & jnp.take(lk, b, axis=0))

        branches = [None] * N_OPCODES
        branches[OP_CONST] = c_const
        branches[OP_LOAD] = c_load
        branches[OP_AND] = c_and
        branches[OP_OR] = c_or
        branches[OP_NOT] = c_not
        branches[OP_EQ] = c_eq
        branches[OP_NE] = c_ne
        branches[OP_LT] = c_lt
        branches[OP_LE] = c_le
        branches[OP_GT] = c_gt
        branches[OP_GE] = c_ge
        branches[OP_ADD] = c_add
        branches[OP_SUB] = c_sub
        branches[OP_MUL] = c_mul
        branches[OP_DIV] = c_div
        branches[OP_IN] = c_in
        ve, vv, vk = jax.lax.switch(op, branches)
        re_ = jax.lax.dynamic_update_index_in_dim(re_, ve, dst, axis=0)
        rv = jax.lax.dynamic_update_index_in_dim(rv, vv, dst, axis=0)
        rk = jax.lax.dynamic_update_index_in_dim(rk, vk, dst, axis=0)
        return (re_, rv, rk), None

    (regs_e, regs_v, regs_k), _ = jax.lax.scan(
        step, (regs_e, regs_v, regs_k),
        (stat["ops"], stat["ime"], stat["imv"]))
    oe = regs_e[meta.out_reg]
    ov = regs_v[meta.out_reg]
    ok = regs_k[meta.out_reg]
    allow = (ok & _truthy(oe, ov)).astype(jnp.uint8)
    missing = ~ok
    return allow, missing


def eval_caveats(metas: tuple, statics: tuple, reqs: tuple,
                 n_rows: int):
    """All caveats' tri-states for one dispatch.

    Returns ``(cav_ok uint8 [n_rows], missing_total int32)``: row 0 is
    the always-valid uncaveated row; missing-context instances read 0
    (fail closed) and count toward the total only on live rows."""
    parts = [jnp.ones(1, dtype=jnp.uint8)]
    missing_total = jnp.int32(0)
    for meta, stat, req in zip(metas, statics, reqs):
        allow, missing = _vm_eval(meta, stat, req)
        parts.append(allow)
        missing_total = missing_total + jnp.sum(
            (missing & stat["real"]).astype(jnp.int32))
    return jnp.concatenate(parts), missing_total
