"""Device-side caveat evaluation: conditional grants as masked tensor ops.

SpiceDB caveats are CEL expressions attached to relationships; a caveated
tuple participates in a check only when its expression evaluates true
under the union of the tuple's stored context and the request's context
(missing context fails CLOSED). The reference evaluates them one
relationship at a time inside the dispatcher; this package compiles each
caveat into a flat op tape evaluated for EVERY caveated tuple in a batch
by a vectorized expression VM (``lax.scan`` over the tape, ``lax.switch``
over opcodes — one jitted program per tape shape, never per caveat), so
the per-tuple tri-state (grant / deny / missing-context) lands in the
same device dispatch as the reachability fixpoint.

Layout:

- :mod:`.ast` — expression grammar (comparisons, boolean ops, arithmetic,
  ``in`` membership, timestamp/ipaddress literals), a recursive-descent
  parser, and the pure-Python tri-state interpreter (the differential
  oracle for the VM);
- :mod:`.compile` — constant folding + lowering to the register tape;
- :mod:`.vm` — the jax evaluator and the host-side instance tables
  (per-tuple context columns, request-context encoding, cache-deadline
  time bounds).
"""

from .ast import (  # noqa: F401
    CaveatDef,
    CaveatError,
    CaveatParam,
    interpret,
    parse_caveat_body,
)
from .compile import CaveatProgram, compile_caveat  # noqa: F401
from .vm import CompiledCaveats, build_caveat_table  # noqa: F401
