"""Caveat expression AST, parser, and tri-state oracle interpreter.

The grammar is the subset of CEL that SpiceDB's stock caveats actually
use, spelled with infix operators (the schema DSL is ours, so ``x in
list`` stands in for CEL's ``list.contains(x)``):

    expr     := or
    or       := and ( '||' and )*
    and      := unary ( '&&' unary )*
    unary    := '!' unary | cmp
    cmp      := sum ( ('=='|'!='|'<'|'<='|'>'|'>=') sum )?
             |  sum 'in' sum
    sum      := prod ( ('+'|'-') prod )*
    prod     := atom ( ('*'|'/') atom )*
    atom     := literal | ident | '(' expr ')' | '[' expr, ... ']'

Every value carries one of the declared parameter types (``int``,
``uint``, ``double``, ``bool``, ``string``, ``timestamp``, ``duration``,
``ipaddress``, ``list<T>``). Scalars lower to float64 — int32/uint32,
unix seconds, interned string ids, and IPv4 addresses are all exact in
f64 — and list membership lowers to per-element [lo, hi] range checks,
which makes CIDR allowlists (``10.0.0.0/8``) ordinary comparisons.

Evaluation is three-valued (SpiceDB's partial-evaluation semantics): a
subexpression over missing context is UNKNOWN; ``&&``/``||`` are Kleene
(false short-circuits unknown, true absorbs it); a top-level UNKNOWN is
the missing-context verdict, which the engine fails closed. The
:func:`interpret` here is the differential oracle the vectorized VM
(:mod:`.vm`) is tested against.
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Optional


class CaveatError(ValueError):
    """Raised on caveat parse/type/encoding failure."""


# ---------------------------------------------------------------------------
# Types
# ---------------------------------------------------------------------------

SCALAR_TYPES = ("int", "uint", "double", "bool", "string", "timestamp",
                "duration", "ipaddress")


@dataclass(frozen=True)
class CaveatType:
    """A declared parameter type: a scalar, or ``list<scalar>``."""

    name: str  # one of SCALAR_TYPES, or "list"
    elem: Optional[str] = None  # list element scalar type

    @property
    def is_list(self) -> bool:
        return self.name == "list"

    def __str__(self) -> str:
        return f"list<{self.elem}>" if self.is_list else self.name


@dataclass(frozen=True)
class CaveatParam:
    name: str
    type: CaveatType


@dataclass(frozen=True)
class CaveatDef:
    """One ``caveat name(params) { expr }`` declaration."""

    name: str
    params: tuple  # tuple[CaveatParam, ...]
    expr: "CavExpr"

    def param(self, name: str) -> Optional[CaveatParam]:
        for p in self.params:
            if p.name == name:
                return p
        return None


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


class CavExpr:
    __slots__ = ()


@dataclass(frozen=True)
class Lit(CavExpr):
    """A literal, already coerced: bool / float scalar, str, or a tuple
    of scalars (list literal). ``type`` is the inferred scalar kind
    ('bool' | 'double' | 'string' | 'list')."""

    value: object
    type: str

    def __str__(self) -> str:
        if self.type == "string":
            return repr(self.value)
        if self.type == "list":
            return "[" + ", ".join(map(str, self.value)) + "]"
        if self.type == "bool":
            return "true" if self.value else "false"
        return repr(self.value)


@dataclass(frozen=True)
class Var(CavExpr):
    name: str

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Un(CavExpr):
    op: str  # '!'
    operand: CavExpr

    def __str__(self) -> str:
        return f"!({self.operand})"


@dataclass(frozen=True)
class Bin(CavExpr):
    op: str  # '&&' '||' '==' '!=' '<' '<=' '>' '>=' '+' '-' '*' '/' 'in'
    left: CavExpr
    right: CavExpr

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


BOOL_OPS = ("&&", "||")
CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
ARITH_OPS = ("+", "-", "*", "/")


# ---------------------------------------------------------------------------
# Tokenizer / parser (shares the schema DSL's token shapes)
# ---------------------------------------------------------------------------

_TOK_RE = re.compile(
    r"""
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<num>\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|==|!=|&&|\|\||[-+*/!<>()\[\],])
    """,
    re.VERBOSE | re.DOTALL,
)


def _tokens(text: str) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOK_RE.match(text, pos)
        if not m:
            raise CaveatError(
                f"caveat expression: unexpected character {text[pos]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        out.append((m.lastgroup, m.group()))
    out.append(("eof", ""))
    return out


class _P:
    def __init__(self, text: str):
        self.toks = _tokens(text)
        self.i = 0

    @property
    def cur(self):
        return self.toks[self.i]

    def eat(self, value: Optional[str] = None) -> str:
        kind, v = self.toks[self.i]
        if value is not None and v != value:
            raise CaveatError(
                f"caveat expression: expected {value!r}, got {v or 'EOF'!r}")
        if kind != "eof":
            self.i += 1
        return v

    def parse(self) -> CavExpr:
        e = self.expr()
        if self.cur[0] != "eof":
            raise CaveatError(
                f"caveat expression: trailing {self.cur[1]!r}")
        return e

    def expr(self) -> CavExpr:
        left = self.and_()
        while self.cur[1] == "||":
            self.eat()
            left = Bin("||", left, self.and_())
        return left

    def and_(self) -> CavExpr:
        left = self.unary()
        while self.cur[1] == "&&":
            self.eat()
            left = Bin("&&", left, self.unary())
        return left

    def unary(self) -> CavExpr:
        if self.cur[1] == "!":
            self.eat()
            return Un("!", self.unary())
        return self.cmp()

    def cmp(self) -> CavExpr:
        left = self.sum()
        v = self.cur[1]
        if v in CMP_OPS:
            self.eat()
            return Bin(v, left, self.sum())
        if v == "in":
            self.eat()
            return Bin("in", left, self.sum())
        return left

    def sum(self) -> CavExpr:
        left = self.prod()
        while self.cur[1] in ("+", "-"):
            op = self.eat()
            left = Bin(op, left, self.prod())
        return left

    def prod(self) -> CavExpr:
        left = self.atom()
        while self.cur[1] in ("*", "/"):
            op = self.eat()
            left = Bin(op, left, self.atom())
        return left

    def atom(self) -> CavExpr:
        kind, v = self.cur
        if v == "(":
            self.eat()
            e = self.expr()
            self.eat(")")
            return e
        if v == "[":
            self.eat()
            items: list = []
            if self.cur[1] != "]":
                while True:
                    it = self.atom()
                    if not isinstance(it, Lit) or it.type == "list":
                        raise CaveatError(
                            "caveat list literals may hold scalars only")
                    items.append(it.value)
                    if self.cur[1] != ",":
                        break
                    self.eat(",")
            self.eat("]")
            return Lit(tuple(items), "list")
        if kind == "num":
            self.eat()
            return Lit(float(v), "double")
        if kind == "str":
            self.eat()
            body = v[1:-1]
            body = re.sub(r"\\(.)", r"\1", body)
            return Lit(body, "string")
        if kind == "ident":
            self.eat()
            if v == "true":
                return Lit(True, "bool")
            if v == "false":
                return Lit(False, "bool")
            return Var(v)
        if v == "-":  # unary minus on a numeric literal
            self.eat()
            inner = self.atom()
            if isinstance(inner, Lit) and inner.type == "double":
                return Lit(-float(inner.value), "double")
            raise CaveatError("unary '-' applies to numeric literals only")
        raise CaveatError(f"caveat expression: unexpected {v or 'EOF'!r}")


def parse_caveat_body(text: str) -> CavExpr:
    """Parse one caveat body (the text between the braces)."""
    return _P(text).parse()


def walk(expr: CavExpr):
    yield expr
    if isinstance(expr, Un):
        yield from walk(expr.operand)
    elif isinstance(expr, Bin):
        yield from walk(expr.left)
        yield from walk(expr.right)


# ---------------------------------------------------------------------------
# Value coercion (shared by the oracle interpreter and the VM encoders)
# ---------------------------------------------------------------------------


def parse_timestamp(v) -> float:
    """RFC3339 (or unix-seconds number) -> unix seconds."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    t = str(v).strip()
    if t.endswith("Z"):
        t = t[:-1] + "+00:00"
    try:
        dt = datetime.fromisoformat(t)
    except ValueError as e:
        raise CaveatError(f"invalid timestamp {v!r}: {e}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(h|ms|m|s)")
_DUR_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3}


def parse_duration(v) -> float:
    """Go-style duration string ("1h30m", "250ms") or number -> seconds."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    t = str(v).strip()
    pos = 0
    total = 0.0
    for m in _DUR_RE.finditer(t):
        if m.start() != pos:
            break
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(t) or pos == 0:
        raise CaveatError(f"invalid duration {v!r}")
    return total


def parse_ip(v) -> float:
    """Dotted-quad IPv4 -> uint32 as float (exact in f64)."""
    try:
        return float(int(ipaddress.IPv4Address(str(v).strip())))
    except (ipaddress.AddressValueError, ValueError) as e:
        raise CaveatError(f"invalid IPv4 address {v!r}: {e}") from None


def parse_cidr_range(v) -> tuple[float, float]:
    """IPv4 address or CIDR -> inclusive [lo, hi] uint32 range."""
    t = str(v).strip()
    try:
        if "/" in t:
            net = ipaddress.IPv4Network(t, strict=False)
            return (float(int(net.network_address)),
                    float(int(net.broadcast_address)))
        a = float(int(ipaddress.IPv4Address(t)))
        return a, a
    except (ipaddress.AddressValueError, ipaddress.NetmaskValueError,
            ValueError) as e:
        raise CaveatError(f"invalid IPv4/CIDR {v!r}: {e}") from None


# -- the 128-bit mapped address space (IPv6 support, ROADMAP PR-9
# -- follow-on): every address — both families — lives in ONE ordered
# -- integer space, the IPv6 space with IPv4 mapped at ::ffff:a.b.c.d.
# -- The VM cannot hold 2^128 on its split planes, so a mapped value is
# -- carried as FOUR 32-bit words (each exact on the planes) and
# -- comparisons lower to word-wise lexicographic checks (compile.py).

_V4_MAPPED_BASE = 0xFFFF00000000  # ::ffff:0:0 as an integer


def parse_ip_mapped(v) -> int:
    """Any IP address (either family) -> its 128-bit mapped integer.
    IPv4 addresses land in the ``::ffff:a.b.c.d`` block so the two
    families order consistently and a bare IPv4 equals its mapped form.
    """
    t = str(v).strip()
    try:
        a = ipaddress.ip_address(t)
    except ValueError as e:
        raise CaveatError(f"invalid IP address {v!r}: {e}") from None
    if isinstance(a, ipaddress.IPv4Address):
        return _V4_MAPPED_BASE + int(a)
    return int(a)


def parse_cidr_range_mapped(v) -> tuple[int, int]:
    """Any address or CIDR (either family) -> inclusive [lo, hi] in the
    128-bit mapped space. An IPv4 CIDR covers exactly its mapped block,
    so a v6 request address can never fall inside a v4 allowlist."""
    t = str(v).strip()
    try:
        if "/" in t:
            net = ipaddress.ip_network(t, strict=False)
            lo, hi = (int(net.network_address),
                      int(net.broadcast_address))
            if isinstance(net, ipaddress.IPv4Network):
                lo, hi = _V4_MAPPED_BASE + lo, _V4_MAPPED_BASE + hi
            return lo, hi
        x = parse_ip_mapped(t)
        return x, x
    except CaveatError:
        raise
    except ValueError as e:
        raise CaveatError(f"invalid IP/CIDR {v!r}: {e}") from None


def ip_words(x: int) -> tuple[int, int, int, int]:
    """A mapped 128-bit address as four big-endian 32-bit words — each
    word exact on the VM's two f32 planes, lexicographic word order ==
    numeric order of the whole address."""
    return ((x >> 96) & 0xFFFFFFFF, (x >> 64) & 0xFFFFFFFF,
            (x >> 32) & 0xFFFFFFFF, x & 0xFFFFFFFF)


def is_v4_mapped(x: int) -> bool:
    return _V4_MAPPED_BASE <= x < _V4_MAPPED_BASE + (1 << 32)


class StringInterner:
    """Host-side string<->code table for caveat string values. Request
    strings never seen in any tuple context or literal get DISTINCT
    negative codes from a per-call :meth:`scratch` view — KNOWN values
    equal to nothing stored (not missing context), and crucially not
    equal to EACH OTHER (one shared sentinel would make any two unseen
    strings compare equal — a fail-open grant)."""

    def __init__(self):
        self._map: dict[str, int] = {}

    def intern(self, s: str) -> int:
        i = self._map.get(s)
        if i is None:
            i = len(self._map)
            self._map[s] = i
        return i

    def lookup(self, s: str) -> int:
        return self._map.get(s, -1)

    def scratch(self) -> "ScratchInterner":
        """A per-evaluation view: known strings resolve to their stored
        codes; unseen strings get fresh distinct negative codes scoped
        to THIS scratch (bounded by the request, never accumulated on
        the shared table)."""
        return ScratchInterner(self)

    def __len__(self) -> int:
        return len(self._map)


class ScratchInterner:
    """Request-scoped code view over a :class:`StringInterner` (see
    :meth:`StringInterner.scratch`). Duck-types the interner surface
    the encoders use."""

    __slots__ = ("_base", "_neg")

    def __init__(self, base: StringInterner):
        self._base = base
        self._neg: dict[str, int] = {}

    def intern(self, s: str) -> int:
        return self._base.intern(s)

    def lookup(self, s: str) -> int:
        i = self._base.lookup(s)
        if i >= 0:
            return i
        got = self._neg.get(s)
        if got is None:
            got = -1 - len(self._neg)
            self._neg[s] = got
        return got

    def __len__(self) -> int:
        return len(self._base)


def encode_scalar(value, typ: str, interner: StringInterner,
                  strict: bool = True) -> float:
    """One context value -> its f64 encoding under a declared scalar
    type. ``strict=False`` (request context) interns nothing new: unknown
    strings become the match-nothing code -1."""
    if typ == "bool":
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if value in (0, 1):
            return float(value)
        raise CaveatError(f"expected bool, got {value!r}")
    if typ in ("int", "uint", "double"):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise CaveatError(f"expected {typ}, got {value!r}")
        return float(value)
    if typ == "string":
        if not isinstance(value, str):
            raise CaveatError(f"expected string, got {value!r}")
        return float(interner.intern(value) if strict
                     else interner.lookup(value))
    if typ == "timestamp":
        return parse_timestamp(value)
    if typ == "duration":
        return parse_duration(value)
    if typ == "ipaddress":
        # the 128-bit MAPPED integer (both families; exact — Python
        # int, compared exactly against int/float by the oracle). The
        # VM never takes this path: ipaddress scalars lower to four
        # 32-bit word columns there (vm.py).
        return parse_ip_mapped(value)
    raise CaveatError(f"unsupported scalar type {typ!r}")


class UnencodableListError(CaveatError):
    """A WELL-TYPED list context the VM's per-instance range tables
    cannot hold (an IPv6 element in ``list<ipaddress>`` — the split
    planes cap at 2^40). The whole list resolves UNKNOWN — missing
    context, fail closed under BOTH polarities. Dropping the element
    instead would narrow the list to a KNOWN answer, which a negated
    membership (``!(ip in blocked)``) would flip into a grant."""


def encode_list(value, elem: str, interner: StringInterner,
                strict: bool = True) -> list[tuple[float, float]]:
    """A context list -> per-element inclusive [lo, hi] ranges (CIDR
    elements span a range; every other element is a point).

    ``ipaddress`` elements encode in the LEGACY uint32 space — the form
    the VM's per-instance list tables hold (the split planes are exact
    to 2^40; a 128-bit mapped value is not). A list containing any
    IPv6 element is therefore UNENCODABLE: it raises
    :class:`UnencodableListError` (counted,
    ``engine_caveat_ipv6_unencodable_total``) and the parameter stays
    UNKNOWN — fail closed whichever way the expression uses it. Scalar
    IPv6 values and LITERAL IPv6 CIDR lists stay exact via the 4-word
    lowering (compile.py)."""
    if not isinstance(value, (list, tuple)):
        raise CaveatError(f"expected list, got {value!r}")
    out: list[tuple[float, float]] = []
    for item in value:
        if elem == "ipaddress":
            try:
                out.append(parse_cidr_range(item))
            except CaveatError:
                # valid IPv6? -> the whole list is unencodable (see
                # class docstring). Anything else is malformed: keep
                # the original strict/lenient behavior.
                parse_cidr_range_mapped(item)  # raises if malformed
                from ..utils.metrics import metrics

                metrics.counter(
                    "engine_caveat_ipv6_unencodable_total").inc()
                raise UnencodableListError(
                    f"IPv6 element {item!r} in a list<ipaddress> "
                    "context (use literal lists for IPv6 CIDRs)"
                ) from None
        else:
            x = encode_scalar(item, elem, interner, strict)
            out.append((x, x))
    return out


# ---------------------------------------------------------------------------
# Tri-state oracle interpreter
# ---------------------------------------------------------------------------

#: the UNKNOWN truth value (missing context)
UNKNOWN = None


def interpret(expr: CavExpr, ctx: dict, params: dict,
              interner: StringInterner) -> Optional[bool]:
    """Evaluate an expression tri-state against raw context values.

    ``ctx`` maps param name -> RAW value (str/number/bool/list); missing
    names are missing context. ``params`` maps name -> CaveatType.
    Returns True / False / None (UNKNOWN). This is the differential
    oracle for the vectorized VM — deliberately scalar and simple.
    """
    if isinstance(interner, StringInterner):
        # per-call scratch: unseen strings get DISTINCT negative codes
        # (mirrors encode_request — never a shared match-all sentinel)
        interner = interner.scratch()

    def enc(name: str):
        if name not in ctx:
            return UNKNOWN
        t = params.get(name)
        if t is None:
            raise CaveatError(f"unknown caveat parameter {name!r}")
        if t.is_list:
            try:
                return encode_list(ctx[name], t.elem, interner,
                                   strict=False)
            except UnencodableListError:
                return UNKNOWN  # mirrors the VM's unknown list column
        return encode_scalar(ctx[name], t.name, interner, strict=False)

    def ev(e: CavExpr):
        if isinstance(e, Lit):
            if e.type == "string":
                return float(interner.lookup(e.value))
            if e.type == "list":
                # element kind is resolved by the compiler; the oracle
                # re-infers: strings intern, numbers are points
                out = []
                for item in e.value:
                    if isinstance(item, str):
                        x = float(interner.lookup(item))
                        out.append((x, x))
                    else:
                        out.append((float(item), float(item)))
                return out
            if e.type == "bool":
                return bool(e.value)
            return float(e.value)
        if isinstance(e, Var):
            return enc(e.name)
        if isinstance(e, Un):
            v = ev(e.operand)
            if v is UNKNOWN:
                return UNKNOWN
            return not _truthy(v)
        assert isinstance(e, Bin)
        if e.op == "&&":
            left, right = ev(e.left), ev(e.right)
            lt = UNKNOWN if left is UNKNOWN else _truthy(left)
            rt = UNKNOWN if right is UNKNOWN else _truthy(right)
            if lt is False or rt is False:
                return False
            if lt is True and rt is True:
                return True
            return UNKNOWN
        if e.op == "||":
            left, right = ev(e.left), ev(e.right)
            lt = UNKNOWN if left is UNKNOWN else _truthy(left)
            rt = UNKNOWN if right is UNKNOWN else _truthy(right)
            if lt is True or rt is True:
                return True
            if lt is False and rt is False:
                return False
            return UNKNOWN
        if e.op == "in":
            # a literal list's elements encode under the LEFT operand's
            # type — exactly like the compiler's list_of: CIDR strings
            # in an ipaddress membership are ranges, not interned codes
            def scalar_type(node):
                if isinstance(node, Var):
                    t = params.get(node.name)
                    return None if t is None or t.is_list else t.name
                if isinstance(node, Lit):
                    return node.type
                return "double"

            left = ev(e.left)
            lt = scalar_type(e.left)
            literal_list = isinstance(e.right, Lit) \
                and e.right.type == "list"
            if literal_list:
                right = []
                for item in e.right.value:
                    if isinstance(item, str):
                        if lt == "ipaddress":
                            # full mapped 128-bit range: literal CIDR
                            # allowlists stay exact for BOTH families
                            right.append(parse_cidr_range_mapped(item))
                        else:
                            x = float(interner.lookup(item))
                            right.append((x, x))
                    else:
                        right.append((float(item), float(item)))
            else:
                right = ev(e.right)
            if left is UNKNOWN or right is UNKNOWN:
                # an UNKNOWN list stays unknown even for a v6 operand:
                # the encoded tables provably hold no v6 elements, but
                # an unencodable (v6-bearing) list might have — a
                # known miss here would fail OPEN under negation
                return UNKNOWN
            if not isinstance(right, list):
                raise CaveatError("'in' needs a list right-hand side")
            if lt == "ipaddress" and not literal_list:
                # param lists hold the legacy uint32 (v4) ranges: a
                # KNOWN list misses any non-v4-mapped operand (it
                # cannot contain v6 elements), a v4-mapped one compares
                # in the uint32 space — the VM's sentinel lowering
                if not is_v4_mapped(int(_num(left))):
                    return False
                x = int(_num(left)) - _V4_MAPPED_BASE
                return any(lo <= x <= hi for lo, hi in right)
            x = _num(left)
            return any(lo <= x <= hi for lo, hi in right)
        left, right = ev(e.left), ev(e.right)
        if left is UNKNOWN or right is UNKNOWN:
            return UNKNOWN
        a, b = _num(left), _num(right)
        if e.op == "==":
            return a == b
        if e.op == "!=":
            return a != b
        if e.op == "<":
            return a < b
        if e.op == "<=":
            return a <= b
        if e.op == ">":
            return a > b
        if e.op == ">=":
            return a >= b
        if e.op == "+":
            return a + b
        if e.op == "-":
            return a - b
        if e.op == "*":
            return a * b
        if e.op == "/":
            if b == 0:
                return UNKNOWN  # division by zero: no verdict, fail closed
            return a / b
        raise CaveatError(f"unknown operator {e.op!r}")

    out = ev(expr)
    if out is UNKNOWN:
        return None
    return _truthy(out)


def _truthy(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, list):
        raise CaveatError("a list is not a boolean caveat result")
    return v != 0.0


def _num(v):
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, list):
        raise CaveatError("a list may only appear on the right of 'in'")
    if isinstance(v, int):
        # mapped 128-bit addresses: Python ints compare exactly against
        # ints AND floats — float() would truncate past 2^53
        return v
    return float(v)
