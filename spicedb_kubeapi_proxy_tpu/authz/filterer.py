"""Response filtering: lists, tables, and single objects.

Mirrors /root/reference/pkg/authz/responsefilterer.go:190-415: after the
upstream responds, list items / table rows / the single object are filtered
against the allowed set computed by the (concurrent) prefilter. Content is
negotiated like the reference (responsefilterer.go:242-313): JSON
(including Table form and unknown/CRD kinds, which are unstructured dicts
here by construction) and kube protobuf — list responses via schema-light
wire surgery on the ``runtime.Unknown`` envelope (proxy/kubeproto.py),
single objects as byte-identical passthrough keyed on the request path.
Filtering errors surface as 401, an excluded single object as 404
(writeResp semantics, responsefilterer.go:716-735).
"""

from __future__ import annotations

import json
from typing import Optional

from ..proxy import kubeproto
from ..proxy.types import ProxyResponse, kube_status
from ..rules.input import ResolveInput
from .lookups import AllowedSet


class FilterError(Exception):
    pass


def _meta_pair(obj: dict) -> tuple[str, str]:
    meta = obj.get("metadata") or {}
    return meta.get("namespace") or "", meta.get("name") or ""


def _filter_list_wire(body: bytes, allowed: AllowedSet):
    """Native wire-level JSON list filtering (graphcore.cpp
    json_list_spans): drop disallowed items by byte span — kept items AND
    the whole wrapper stay byte-identical, and a 15 MB 100k-item body
    never goes through json.loads (~4x faster; numbers in
    bench_results/proxy_path_r5_cpu.json). Handles *List bodies (items,
    metadata at item top level) and Tables (rows, metadata under each
    row's ``object``). Returns (status, new_body) or None to fall back
    to the Python path (scanner bailed, single objects, native
    unavailable)."""
    from .. import native

    # cheap kind sniff picks the scan key so the common case is ONE pass
    # (a Table with unusual kind spacing just pays a second scan)
    looks_table = b'"kind":"Table"' in body or b'"kind": "Table"' in body
    first_key, first_nested = (b"rows", True) if looks_table \
        else (b"items", False)
    scan = native.json_list_spans(body, first_key, nested=first_nested)
    if scan is None:
        return None
    kind_b, arr_span, item_spans, keys = scan
    kind = kind_b.decode("utf-8", "replace")
    if (kind == "Table") != looks_table:
        # sniff guessed wrong: rescan with the other key
        key, nested = (b"rows", True) if kind == "Table" \
            else (b"items", False)
        scan = native.json_list_spans(body, key, nested=nested)
        if scan is None:
            return None
        _, arr_span, item_spans, keys = scan
    if kind != "Table" and not kind.endswith("List"):
        return None  # single objects: Python path
    if arr_span[0] < 0:
        # kind says list/table but the array key is absent: nothing to
        # filter (`doc.get(...) or []` semantics) — body passes through
        return 200, body
    # per-item records [esc] ns 0x1f name 0x1e, split in ONE C call; an
    # unescaped item's WHOLE record compares against the precomputed
    # record set — one set lookup, no per-item slicing or decoding
    # (escaped names, rare, take the exact json.loads route)
    recs = keys.split(b"\x1e")
    pairs_rec = allowed.pairs_records()
    pairs = allowed.pairs
    loads = json.loads
    kept_idx: list = []
    dropped = False
    idx = 0
    for rec in recs[:len(recs) - 1]:
        if rec in pairs_rec:
            ok = True
        elif rec[0] == 0x31:  # b'1': escapes present, decode exactly
            ns_b, _, nm_b = rec[1:].partition(b"\x1f")
            try:
                ns = loads(b'"%s"' % ns_b) if b"\\" in ns_b \
                    else ns_b.decode("utf-8")
                nm = loads(b'"%s"' % nm_b) if b"\\" in nm_b \
                    else nm_b.decode("utf-8")
            except ValueError:
                # invalid escape / invalid utf-8: json.loads would have
                # rejected the whole body — fall back so the Python path
                # produces its clean 401, not an unhandled 500
                return None
            ok = (ns, nm) in pairs
        else:
            ok = False
        if ok:
            kept_idx.append(idx)
        else:
            dropped = True
        idx += 1
    if not dropped:
        return 200, body  # byte-identical passthrough
    spans = item_spans[kept_idx].tolist() if kept_idx else []
    parts = [body[:int(arr_span[0])],
             b",".join(body[s:e] for s, e in spans),
             body[int(arr_span[1]):]]
    return 200, b"".join(parts)


def filter_body(body: bytes, allowed: AllowedSet,
                input: ResolveInput) -> tuple[int, bytes]:
    """Filter a JSON response body; returns (status, new_body)."""
    wire = _filter_list_wire(body, allowed)
    if wire is not None:
        return wire
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise FilterError(f"response is not JSON: {e}") from None
    if not isinstance(doc, dict):
        raise FilterError("response is not an object")
    kind = doc.get("kind", "")
    if kind == "Table":
        rows = doc.get("rows") or []
        kept = []
        for row in rows:
            obj = row.get("object") or {}
            ns, name = _meta_pair(obj)
            if allowed.allows(ns, name):
                kept.append(row)
        if len(kept) == len(rows):
            return 200, body  # nothing dropped: byte-identical
        doc["rows"] = kept
        return 200, json.dumps(doc).encode()
    if kind.endswith("List"):
        items = doc.get("items") or []
        kept = [o for o in items if allowed.allows(*_meta_pair(o))]
        if len(kept) == len(items):
            # nothing dropped — the common admin/owner case: skip the
            # re-serialize of a multi-MB body and keep bytes identical
            return 200, body
        doc["items"] = kept
        return 200, json.dumps(doc).encode()
    # single object
    ns, name = _meta_pair(doc)
    if allowed.allows(ns, name):
        return 200, body
    return 404, b""


def _filter_proto_list_native(body: bytes, raw: bytes,
                              allowed: AllowedSet, table: bool = False):
    """Native proto list/Table filtering (graphcore.cpp
    proto_list_spans / proto_table_spans): same record-set comparison
    as the JSON wire path, ~12x faster than the pure-Python varint
    walker at 100k items. Returns (status, new_body) or None to fall
    back (scanner bailed)."""
    from .. import native

    scan = native.proto_table_spans(raw) if table \
        else native.proto_list_spans(raw)
    if scan is None:
        return None
    item_spans, keys = scan
    recs = keys.split(b"\x1e")
    pairs_rec = allowed.pairs_records()
    drop_spans: list = []
    idx = 0
    for rec in recs[:len(recs) - 1]:
        if rec not in pairs_rec:
            drop_spans.append(idx)
        idx += 1
    if not drop_spans:
        return 200, body  # byte-identical passthrough
    spans = item_spans[drop_spans].tolist()
    parts = []
    pos = 0
    for s, e in spans:
        parts.append(raw[pos:s])
        pos = e
    parts.append(raw[pos:])
    return 200, kubeproto.replace_unknown_raw(body, b"".join(parts))


def filter_body_proto(body: bytes, allowed: AllowedSet,
                      input: ResolveInput) -> tuple[int, bytes]:
    """Filter a kube-protobuf response body; returns (status, new_body).

    Lists are filtered by dropping disallowed ``items`` from the inner
    message (kept bytes are untouched); single objects never need parsing
    — the request path already names the object, so the decision is the
    allowed-set test and the body passes through byte-identical."""
    try:
        _, kind, raw = kubeproto.decode_unknown(body)
        if kind == "Table":
            # rows filtered at the wire level (kept rows byte-identical);
            # an un-keyable row (includeObject=None) raises ProtoError ->
            # a clean 401, never a 500 (reference decodes the full Table,
            # responsefilterer.go:349-374)
            wire = _filter_proto_list_native(body, raw, allowed,
                                             table=True)
            if wire is not None:
                return wire
            new_raw = kubeproto.filter_table_raw(raw, allowed.allows)
            return 200, kubeproto.replace_unknown_raw(body, new_raw)
        if kind.endswith("List"):
            wire = _filter_proto_list_native(body, raw, allowed)
            if wire is not None:
                return wire
            new_raw = kubeproto.filter_list_raw(raw, allowed.allows)
            return 200, kubeproto.replace_unknown_raw(body, new_raw)
    except kubeproto.ProtoError as e:
        raise FilterError(f"malformed kube protobuf response: {e}") \
            from None
    # single object: keyed on the request path, body untouched
    if allowed.allows(input.namespace or "", input.name or ""):
        return 200, body
    return 404, b""


def apply_filter(resp: ProxyResponse, allowed: AllowedSet,
                 input: ResolveInput) -> ProxyResponse:
    """Filter an upstream response in place (the reference hooks
    ReverseProxy.ModifyResponse, pkg/proxy/server.go:103-112)."""
    if resp.status != 200:
        return resp  # upstream errors pass through unfiltered
    ctype = resp.content_type
    try:
        if ctype and "protobuf" in ctype:
            status, body = filter_body_proto(resp.body, allowed, input)
        elif ctype and "json" not in ctype:
            return kube_status(
                401, f"cannot filter content type {ctype!r}")
        else:
            status, body = filter_body(resp.body, allowed, input)
    except FilterError as e:
        return kube_status(401, str(e))
    if status == 404:
        info = input.request
        return kube_status(
            404,
            f'{info.resource} "{input.name}" not found',
            "NotFound",
        )
    headers = dict(resp.headers)
    headers["Content-Length"] = str(len(body))
    return ProxyResponse(status=200, headers=headers, body=body)
