"""Response filtering: lists, tables, and single objects.

Mirrors /root/reference/pkg/authz/responsefilterer.go:190-415: after the
upstream responds, list items / table rows / the single object are filtered
against the allowed set computed by the (concurrent) prefilter. JSON is the
negotiated content type (the reference additionally handles kube protobuf;
this proxy requests/serves JSON). Filtering errors surface as 401, an
excluded single object as 404 (writeResp semantics,
responsefilterer.go:716-735 — the reference writes 401 for errors and 404
for a filtered-out single object).
"""

from __future__ import annotations

import json
from typing import Optional

from ..proxy.types import ProxyResponse, kube_status
from ..rules.input import ResolveInput
from .lookups import AllowedSet


class FilterError(Exception):
    pass


def _meta_pair(obj: dict) -> tuple[str, str]:
    meta = obj.get("metadata") or {}
    return meta.get("namespace") or "", meta.get("name") or ""


def filter_body(body: bytes, allowed: AllowedSet,
                input: ResolveInput) -> tuple[int, bytes]:
    """Filter a JSON response body; returns (status, new_body)."""
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise FilterError(f"response is not JSON: {e}") from None
    if not isinstance(doc, dict):
        raise FilterError("response is not an object")
    kind = doc.get("kind", "")
    if kind == "Table":
        rows = doc.get("rows") or []
        kept = []
        for row in rows:
            obj = row.get("object") or {}
            ns, name = _meta_pair(obj)
            if allowed.allows(ns, name):
                kept.append(row)
        doc["rows"] = kept
        return 200, json.dumps(doc).encode()
    if kind.endswith("List"):
        items = doc.get("items") or []
        kept = [o for o in items if allowed.allows(*_meta_pair(o))]
        doc["items"] = kept
        return 200, json.dumps(doc).encode()
    # single object
    ns, name = _meta_pair(doc)
    if allowed.allows(ns, name):
        return 200, body
    return 404, b""


def apply_filter(resp: ProxyResponse, allowed: AllowedSet,
                 input: ResolveInput) -> ProxyResponse:
    """Filter an upstream response in place (the reference hooks
    ReverseProxy.ModifyResponse, pkg/proxy/server.go:103-112)."""
    if resp.status != 200:
        return resp  # upstream errors pass through unfiltered
    ctype = resp.content_type
    if ctype and "json" not in ctype:
        # the proxy always requests JSON upstream; anything else is a bug
        return kube_status(401, f"cannot filter content type {ctype!r}")
    try:
        status, body = filter_body(resp.body, allowed, input)
    except FilterError as e:
        return kube_status(401, str(e))
    if status == 404:
        info = input.request
        return kube_status(
            404,
            f'{info.resource} "{input.name}" not found',
            "NotFound",
        )
    headers = dict(resp.headers)
    headers["Content-Length"] = str(len(body))
    return ProxyResponse(status=200, headers=headers, body=body)
