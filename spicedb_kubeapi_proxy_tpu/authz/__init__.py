"""Per-request authorization: middleware orchestration + response filtering.

Mirrors the reference's pkg/authz: WithAuthorization per-request flow
(checks, update dispatch, prefilter/postfilter/watch paths), LookupResources
prefiltering, list/table/object response filtering, bulk postfilter checks,
and the dual-write front door.
"""

from .middleware import AuthzDeps, authorize  # noqa: F401
from .lookups import AllowedSet, run_prefilter  # noqa: F401
