"""Check execution: rule templates -> one bulk permission query.

Mirrors /root/reference/pkg/authz/check.go:17-114: every matching rule's
check (or postcheck) templates generate relationships; ALL of them must
come back permitted. The reference fans out goroutines that each issue a
CheckBulkPermissions RPC; here the entire set is one engine.check_bulk
call — a single batched fixpoint on device.
"""

from __future__ import annotations

from typing import Optional

from ..engine import CheckItem, Engine
from ..rules.compile import RelationshipExpr, RunnableRule
from ..rules.input import ResolveInput


def collect_check_items(exprs: list[RelationshipExpr],
                        input: ResolveInput) -> list[CheckItem]:
    items: list[CheckItem] = []
    for e in exprs:
        for rel in e.generate(input):
            items.append(CheckItem(
                rel.resource_type, rel.resource_id, rel.resource_relation,
                rel.subject_type, rel.subject_id,
                rel.subject_relation or None,
            ))
    return items


def collect_all_items(rules: list[RunnableRule], input: ResolveInput,
                      post: bool = False) -> list[CheckItem]:
    items: list[CheckItem] = []
    for r in rules:
        items.extend(collect_check_items(
            r.post_checks if post else r.checks, input))
    return items


def run_checks(engine: Engine, rules: list[RunnableRule],
               input: ResolveInput, post: bool = False,
               items: Optional[list[CheckItem]] = None,
               context: Optional[dict] = None) -> bool:
    """True iff every generated check passes (fully consistent).
    ``items`` skips re-generating the check relationships when the caller
    already collected them (the cached-probe fast path). ``context`` is
    the request's caveat context (client IP, caller attributes) gating
    conditional grants — missing context fails closed at the engine."""
    if items is None:
        items = collect_all_items(rules, input, post)
    if not items:
        return True
    if context:
        return all(engine.check_bulk(items, context=context))
    return all(engine.check_bulk(items))


def cached_verdict(engine: Engine, rules: list[RunnableRule],
                   input: ResolveInput, post: bool = False,
                   context: Optional[dict] = None
                   ) -> tuple[list[CheckItem], Optional[bool]]:
    """Non-blocking decision-cache probe: ``(items, verdict)`` where
    ``verdict`` is the combined answer when EVERY generated check hit the
    engine's decision cache, else ``None`` (caller falls back to
    :func:`run_checks` off-loop — the probe never dispatches or blocks,
    so the middleware can run it on the event loop and skip the
    ``asyncio.to_thread`` hop entirely on a full hit). Contexted
    requests probe under their context digest — a conditional verdict
    can never be served across contexts."""
    items = collect_all_items(rules, input, post)
    if not items:
        return items, True
    probe = getattr(engine, "try_cached_check", None)
    if probe is None:  # remote engines have no local cache to probe
        return items, None
    got = probe(items, context=context) if context else probe(items)
    if got is None:
        return items, None
    return items, all(got)


def has_checks(rules: list[RunnableRule]) -> bool:
    return any(r.checks for r in rules)
