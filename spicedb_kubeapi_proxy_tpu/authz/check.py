"""Check execution: rule templates -> one bulk permission query.

Mirrors /root/reference/pkg/authz/check.go:17-114: every matching rule's
check (or postcheck) templates generate relationships; ALL of them must
come back permitted. The reference fans out goroutines that each issue a
CheckBulkPermissions RPC; here the entire set is one engine.check_bulk
call — a single batched fixpoint on device.
"""

from __future__ import annotations

from ..engine import CheckItem, Engine
from ..rules.compile import RelationshipExpr, RunnableRule
from ..rules.input import ResolveInput


def collect_check_items(exprs: list[RelationshipExpr],
                        input: ResolveInput) -> list[CheckItem]:
    items: list[CheckItem] = []
    for e in exprs:
        for rel in e.generate(input):
            items.append(CheckItem(
                rel.resource_type, rel.resource_id, rel.resource_relation,
                rel.subject_type, rel.subject_id,
                rel.subject_relation or None,
            ))
    return items


def run_checks(engine: Engine, rules: list[RunnableRule],
               input: ResolveInput, post: bool = False) -> bool:
    """True iff every generated check passes (fully consistent)."""
    items: list[CheckItem] = []
    for r in rules:
        items.extend(collect_check_items(
            r.post_checks if post else r.checks, input))
    if not items:
        return True
    return all(engine.check_bulk(items))


def has_checks(rules: list[RunnableRule]) -> bool:
    return any(r.checks for r in rules)
