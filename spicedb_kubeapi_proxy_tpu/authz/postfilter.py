"""PostFilter: per-item bulk permission checks over list responses.

Mirrors /root/reference/pkg/authz/postfilter.go:17-182: the recorded list
(or table) response is parsed, ONE CheckBulkPermissions request is built
covering every item x every postfilter rule, and items whose checks all
pass are kept. On TPU the whole bulk is a single fixpoint pass
(engine.check_bulk), so cost is one device round trip regardless of list
size.
"""

from __future__ import annotations

import dataclasses
import json

from ..engine import CheckItem, Engine
from ..rules.compile import PostFilter
from ..rules.input import ResolveInput
from ..proxy.types import ProxyResponse, kube_status


def _item_input(input: ResolveInput, obj: dict) -> ResolveInput:
    """Per-item ResolveInput: the item's metadata drives name/namespace
    (reference postfilter.go builds per-object inputs)."""
    meta = obj.get("metadata") or {}
    name = meta.get("name") or ""
    ns = meta.get("namespace") or ""
    if input.request.resource == "namespaces":
        ns = ""
    nsname = f"{ns}/{name}" if ns else name
    return dataclasses.replace(
        input, name=name, namespace=ns, namespaced_name=nsname, object=obj,
    )


def filter_list_response(engine: Engine, post_filters: list[PostFilter],
                         input: ResolveInput,
                         resp: ProxyResponse,
                         context: dict = None) -> ProxyResponse:
    if resp.status != 200:
        return resp
    try:
        doc = json.loads(resp.body)
    except ValueError:
        return kube_status(401, "postfilter: response is not JSON")
    kind = doc.get("kind", "")
    if kind == "Table":
        entries = doc.get("rows") or []
        objs = [(row.get("object") or {}) for row in entries]
    elif kind.endswith("List"):
        entries = doc.get("items") or []
        objs = entries
    else:
        return kube_status(401, f"postfilter: unexpected kind {kind!r}")

    # one bulk check covering items x rules (postfilter.go:58-182)
    items: list[CheckItem] = []
    item_index: list[int] = []  # check index -> entry index
    for i, obj in enumerate(objs):
        per_item = _item_input(input, obj)
        for pf in post_filters:
            for rel in pf.rel.generate(per_item):
                items.append(CheckItem(
                    rel.resource_type, rel.resource_id, rel.resource_relation,
                    rel.subject_type, rel.subject_id,
                    rel.subject_relation or None,
                ))
                item_index.append(i)
    results = (engine.check_bulk(items, context=context) if context
               else engine.check_bulk(items))
    ok = [True] * len(objs)
    for ci, passed in enumerate(results):
        if not passed:
            ok[item_index[ci]] = False
    kept = [e for i, e in enumerate(entries) if ok[i]]
    if kind == "Table":
        doc["rows"] = kept
    else:
        doc["items"] = kept
    body = json.dumps(doc).encode()
    headers = dict(resp.headers)
    headers["Content-Length"] = str(len(body))
    return ProxyResponse(status=200, headers=headers, body=body)
