"""PreFilter execution: LookupResources -> allowed (namespace, name) set.

Mirrors /root/reference/pkg/authz/lookups.go:43-136: the prefilter rule's
relationship template must resolve its resource ID to ``$`` (the
match-everything marker); the engine's reverse-reachability query returns
every object id the subject can reach, and the rule's
``fromObjectIDNameExpr`` / ``fromObjectIDNamespaceExpr`` expressions map
each id to an allowed NamespacedName.

The TPU twist (BASELINE.json north star): instead of streaming ids over
gRPC and mapping one-by-one, the engine hands back a boolean mask over the
type's whole interned object space from a single device pass; when the
mapping expressions are the identity/split forms (the common case, e.g.
deploy/rules.yaml), names are materialized lazily only for allowed ids.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger(__name__)

from ..engine import Engine
from ..rules.compile import PreFilter, RunnableRule
from ..rules.expr import ExprError
from ..rules.input import ResolveInput
from ..rules.proxyrule import MATCHING_ID_FIELD_VALUE


class PreFilterError(Exception):
    pass


@dataclass
class AllowedSet:
    """Allowed (namespace, name) pairs; namespace '' for cluster-scoped."""

    pairs: set = field(default_factory=set)
    # lazy utf-8 view for the native wire filter (authz/filterer.py):
    # comparing raw JSON string bytes against encoded pairs skips a
    # per-item decode on the hot loop
    _pairs_bytes: Optional[set] = field(default=None, repr=False,
                                        compare=False)

    def add(self, namespace: str, name: str) -> None:
        self.pairs.add((namespace or "", name))
        self._pairs_bytes = None

    def allows(self, namespace: str, name: str) -> bool:
        return (namespace or "", name) in self.pairs

    def pairs_records(self) -> set:
        """Packed ``b"0" + ns + 0x1f + name`` records, the native wire
        filter's per-item key format — a kept item is ONE set lookup on
        the already-materialized record bytes, no per-item slicing."""
        if self._pairs_bytes is None:
            out = set()
            for ns, n in self.pairs:
                try:
                    out.add(b"0%s\x1f%s" % (ns.encode("utf-8"),
                                            n.encode("utf-8")))
                except UnicodeEncodeError:
                    # lone surrogates cannot appear in an UNESCAPED
                    # record (the scanner validates utf-8); items naming
                    # them arrive escape-flagged and compare via the
                    # decoded-str path against .pairs
                    pass
            self._pairs_bytes = out
        return self._pairs_bytes

    def __len__(self) -> int:
        return len(self.pairs)


def single_prefilter(rules: list[RunnableRule]) -> Optional[tuple[RunnableRule, PreFilter]]:
    """At most one prefilter may apply to a request (reference
    singlePreFilterRule, pkg/authz/rules.go:49-61)."""
    found: list[tuple[RunnableRule, PreFilter]] = []
    for r in rules:
        for p in r.pre_filters:
            found.append((r, p))
    if not found:
        return None
    if len(found) > 1:
        raise PreFilterError(
            f"multiple prefilter rules match the request "
            f"({[r.name for r, _ in found]}); only one is allowed")
    return found[0]


def run_prefilter_sync(engine: Engine, pf: PreFilter,
                       input: ResolveInput,
                       strict: bool = True, lookup=None,
                       context: Optional[dict] = None) -> AllowedSet:
    """``strict=False`` skips ids whose name/namespace mapping expression
    fails instead of raising — for MID-STREAM recomputes, where one
    unmappable id must not freeze the allowed set (a frozen set fails
    OPEN for revocations). The initial, pre-headers run stays strict so
    misconfigured mappings surface as a 500.

    ``lookup`` overrides the engine call with ``lookup(rel) -> [ids]`` —
    the watch hub routes group recomputes through a shared
    :class:`~..engine.batcher.LookupBatcher` this way, so N groups
    triggered by one write batch fuse into ~N/8 device fixpoints
    instead of N (authz/watchhub.py). Results are unconditional by
    construction (caveated tuples never enter the store —
    models/bootstrap.py / engine._validate — so there are no
    CONDITIONAL results to skip; the reference's lookups.go:83-90 skip
    happens here at ingest instead)."""
    rel = pf.rel.generate(input)[0]
    if rel.resource_id != MATCHING_ID_FIELD_VALUE:
        raise PreFilterError(
            f"prefilter resource ID must be {MATCHING_ID_FIELD_VALUE!r}, "
            f"got {rel.resource_id!r} (reference lookups.go:49-56)")
    if lookup is not None:
        # shared-batcher recomputes (watch hub) carry no request context:
        # conditional grants resolve from tuple context alone, missing
        # request-only parameters fail closed — the safe direction for a
        # mid-stream allowed-set refresh
        ids = lookup(rel)
    elif context:
        ids = engine.lookup_resources(
            rel.resource_type, rel.resource_relation,
            rel.subject_type, rel.subject_id, rel.subject_relation or None,
            context=context,
        )
    else:
        ids = engine.lookup_resources(
            rel.resource_type, rel.resource_relation,
            rel.subject_type, rel.subject_id, rel.subject_relation or None,
        )
    allowed = AllowedSet()
    pairs = allowed.pairs
    # Vectorized fast paths for the dominant mapping forms, classified
    # ONCE at rule compile time (rules/compile.py _mapping_kind — the
    # deploy/rules.yaml shapes): at 100k allowed ids the general loop's
    # per-id expression evaluation is the proxy-side cost of a big list
    # filter, and these forms compute the same pairs with plain string
    # ops. Split semantics match expr.py's split_name/split_namespace
    # exactly (first '/' splits; no '/' => cluster-scoped).
    kind = getattr(pf, "mapping_kind", "general")
    if kind == "identity":
        pairs.update(("", obj_id) for obj_id in ids)
        allowed._pairs_bytes = None  # direct .pairs mutation: keep the
        return allowed               # record cache coherent
    if kind == "split":
        for obj_id in ids:
            ns, sep, nm = obj_id.partition("/")
            pairs.add((ns, nm) if sep else ("", obj_id))
        allowed._pairs_bytes = None
        return allowed
    base = input.template_data()
    # one mutable data map, not a copy per id: the exprs only read it,
    # and only resourceId changes between iterations
    data = dict(base)
    name_eval = pf.name_expr.evaluate_str
    ns_eval = pf.namespace_expr.evaluate_str if pf.namespace_expr else None
    skipped = 0
    for obj_id in ids:
        data["resourceId"] = obj_id
        try:
            name = name_eval(data)
            ns = ns_eval(data) if ns_eval else ""
        except ExprError as e:
            if strict:
                raise PreFilterError(
                    f"mapping looked-up id {obj_id!r}: {e}") from None
            # fail-closed skip, but never silently: without a log line a
            # mapping bug surfacing mid-stream would just make objects
            # vanish from watches with nothing to debug from
            skipped += 1
            if skipped == 1:
                log.warning("prefilter id mapping failed for %r "
                            "(skipping; fails closed): %s", obj_id, e)
            continue
        pairs.add((ns or "", name))
    allowed._pairs_bytes = None  # direct .pairs mutation (see fast paths)
    if skipped > 1:
        log.warning("prefilter id mapping skipped %d more ids", skipped - 1)
    return allowed


async def run_prefilter(engine: Engine, pf: PreFilter,
                        input: ResolveInput,
                        strict: bool = True, lookup=None,
                        context: Optional[dict] = None) -> AllowedSet:
    """Async wrapper so the device query overlaps the upstream kube request
    (the reference overlaps via goroutine+channel,
    responsefilterer.go:165-183)."""
    return await asyncio.to_thread(run_prefilter_sync, engine, pf, input,
                                   strict, lookup, context)
