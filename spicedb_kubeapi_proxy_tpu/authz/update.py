"""Update path: resolve a rule's update set and launch the dual-write.

Mirrors /root/reference/pkg/authz/update.go:53-271: creates/touches/deletes
(including tupleSet expansion), preconditions and deleteByFilter templates
with the ``$``-dollar wildcard convention ($resourceType/$resourceID/
$resourceRelation/$subjectType/$subjectID/$subjectRelation mean "any"),
resolved against the request input, then handed to the workflow engine;
the caller waits up to 30s for the result.
"""

from __future__ import annotations

import base64
from typing import Optional

from ..dtx.workflow import WorkflowInput
from ..rules.compile import RelationshipExpr, ResolvedRel, RunnableRule, UpdateSet
from ..rules.input import ResolveInput

DOLLAR_FIELDS = {
    "$resourceType", "$resourceID", "$resourceRelation",
    "$subjectType", "$subjectID", "$subjectRelation",
}


class UpdateError(Exception):
    pass


def single_update_rule(rules: list[RunnableRule]) -> Optional[RunnableRule]:
    """At most one rule with updates may match (reference singleUpdateRule,
    pkg/authz/rules.go:21-35)."""
    found = [r for r in rules if not r.update.empty()]
    if not found:
        return None
    if len(found) > 1:
        raise UpdateError(
            f"multiple update rules match the request "
            f"({[r.name for r in found]}); only one is allowed")
    return found[0]


def _rels(exprs: list[RelationshipExpr], input: ResolveInput) -> list[str]:
    out: list[str] = []
    for e in exprs:
        for rel in e.generate(input):
            out.append(str(rel))
    return out


def _filter_from_rel(rel: ResolvedRel, where: str) -> dict:
    """Template fields equal to a ``$``-dollar value (or bare ``$``) mean
    "match any" (reference filterFromRel, update.go:207-271)."""

    def f(value: str, dollar: str) -> Optional[str]:
        if value in ("", "$", dollar):
            return None
        return value

    out = {
        "resource_type": f(rel.resource_type, "$resourceType"),
        "resource_id": f(rel.resource_id, "$resourceID"),
        "relation": f(rel.resource_relation, "$resourceRelation"),
        "subject_type": f(rel.subject_type, "$subjectType"),
        "subject_id": f(rel.subject_id, "$subjectID"),
        "subject_relation": f(rel.subject_relation, "$subjectRelation"),
    }
    if out["resource_type"] is None:
        raise UpdateError(f"{where}: resource type may not be a wildcard")
    return out


def _precondition_dicts(update: UpdateSet, input: ResolveInput) -> list[dict]:
    out = []
    for must_exist, exprs in ((True, update.preconditions_exist),
                              (False, update.preconditions_do_not_exist)):
        for e in exprs:
            for rel in e.generate(input):
                out.append({
                    "must_exist": must_exist,
                    "filter": _filter_from_rel(rel, "precondition"),
                })
    return out


def build_workflow_input(rule: RunnableRule, input: ResolveInput,
                         uri: str, headers: dict) -> WorkflowInput:
    u = rule.update
    return WorkflowInput(
        verb=input.request.verb,
        path=input.request.path,
        uri=uri,
        headers={k: v for k, v in headers.items()
                 if not k.lower().startswith("x-remote-")},
        user_name=input.user.name,
        object_name=input.name,
        namespace=input.namespace,
        api_group=input.request.api_group,
        resource=input.request.resource,
        body_b64=base64.b64encode(input.body).decode() if input.body else "",
        preconditions=_precondition_dicts(u, input),
        creates=_rels(u.creates, input),
        touches=_rels(u.touches, input),
        deletes=_rels(u.deletes, input),
        delete_by_filter=[
            _filter_from_rel(rel, "deleteByFilter")
            for e in u.delete_by_filter
            for rel in e.generate(input)
        ],
    )
