"""Filtered watch: join the upstream watch stream with live permission
updates from the engine.

Mirrors the reference's dual-stream join
(/root/reference/pkg/authz/watch.go:27-111 and
responsefilterer.go:434-714): one side consumes relationship-update events
from the engine (the SpiceDB Watch API role) and recomputes the allowed
set — one device query for the WHOLE set per event batch, which also
catches grants/revocations mediated through arrows and usersets that the
reference's per-object re-checks of same-type events cannot see; the
other side decodes upstream watch frames, passing frames for allowed
objects through byte-identical, buffering the latest frame of
not-yet-allowed objects (flushed on an allow transition, dropped on
deny).

The engine side rides the shared :class:`~.watchhub.WatchHub`: one event
pump per engine (store-condition push in-process, server-push stream for
``tcp://`` hosts — no polling) and ONE allowed-set recompute per distinct
(prefilter rule, subject) group per relevant event batch, fanned out to
every watcher in the group. The per-watcher loop below sleeps on a single
queue carrying both upstream frames and hub updates — zero idle wakeups.
"""

from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator, Optional

from ..engine import Engine
from ..proxy import kubeproto
from ..rules.compile import PreFilter

from ..rules.input import ResolveInput
from ..proxy.types import ProxyRequest, ProxyResponse
from .lookups import AllowedSet, run_prefilter
from .watchhub import WatchHub


async def filtered_watch(engine: Engine, upstream_resp: ProxyResponse,
                         pf: PreFilter, input: ResolveInput,
                         poll_interval: float = 0.05,
                         hub: Optional[WatchHub] = None) -> ProxyResponse:
    """Wrap an upstream watch response with permission filtering."""
    if upstream_resp.status != 200 or upstream_resp.stream is None:
        return upstream_resp

    # A private hub for direct callers (tests); the middleware passes the
    # proxy-wide hub so recomputes are shared across watchers.
    if hub is None:
        hub = WatchHub(engine, poll_interval)

    # The prefilter runs eagerly (not inside the streaming generator) so
    # PreFilterError surfaces as a 500 before the 200/chunked headers are
    # committed. Hub registration happens INSIDE the generator instead: a
    # stream the client abandons before the first frame is a generator
    # that never starts, and PEP 525 never runs its finally — an eager
    # registration would leak the watcher (and its queue) forever. The
    # snapshot→registration event gap is closed by hub.refresh() below.
    allowed = await run_prefilter(engine, pf, input)

    async def frames() -> AsyncIterator[bytes]:
        nonlocal allowed
        handle = await hub.register(pf, input)
        # one forced, ordered recompute: initial frames are HELD until it
        # lands, so grants/revocations that raced the initial snapshot
        # (or tick recomputes in flight across registration) can never
        # judge a frame with stale state
        await hub.refresh(handle)
        buffered: dict[tuple, bytes] = {}
        # frames held while a recompute covering an earlier event batch is
        # in flight — a revoked object's frame must be judged against the
        # POST-event allowed set, not race the device query (("pending")
        # markers from the hub; same ordering the old per-watcher loop
        # got by draining events before frames)
        held: list[bytes] = []
        waiting_for = handle.reg_seq  # highest pending seq seen
        applied = handle.reg_seq  # highest seq an applied set covers
        q = handle.queue  # hub updates AND upstream frames land here

        async def read_upstream():
            try:
                async for chunk in upstream_resp.stream:
                    q.put_nowait(("frame", chunk))
            finally:
                q.put_nowait(("frame", None))

        def emit(frame: bytes) -> Optional[bytes]:
            key = _frame_object_key(frame, pf)
            if key is None or allowed.allows(*key):
                return frame  # byte-identical passthrough
            buffered[key] = frame
            return None

        reader = asyncio.get_running_loop().create_task(read_upstream())
        try:
            while True:
                item = await q.get()
                kind = item[0]
                if kind == "frame":
                    frame = item[1]
                    if frame is None:
                        return  # upstream ended
                    if waiting_for > applied:
                        held.append(frame)
                        continue
                    try:
                        out = emit(frame)
                    except kubeproto.ProtoError:
                        # a proto frame we cannot judge (no keyable
                        # object): end the stream instead of leaking it —
                        # the client re-lists and re-watches
                        return
                    if out is not None:
                        yield out
                elif kind == "pending":
                    waiting_for = max(waiting_for, item[1])
                elif kind == "allowed":
                    if item[2] <= handle.reg_seq:
                        # strictly predates (or is concurrent with) our
                        # initial snapshot — e.g. an expiry-tick recompute
                        # already in flight at registration; our refresh's
                        # covering set (seq > reg_seq) is on its way
                        continue
                    fresh: AllowedSet = item[1]
                    for key in fresh.pairs - allowed.pairs:
                        frame = buffered.pop(key, None)
                        if frame is not None:
                            yield frame
                    for key in allowed.pairs - fresh.pairs:
                        buffered.pop(key, None)
                    allowed = fresh
                    applied = max(applied, item[2])
                    if applied >= waiting_for and held:
                        for frame in held:
                            try:
                                out = emit(frame)
                            except kubeproto.ProtoError:
                                return  # as above: never leak unjudged
                            if out is not None:
                                yield out
                        held = []
                else:  # "error": shared recompute or event pump died —
                    return  # end the stream; the client re-lists+rewatches
        finally:
            reader.cancel()
            await hub.unregister(handle)

    return ProxyResponse(status=200, headers=dict(upstream_resp.headers),
                         stream=frames())


def _frame_object_key(frame: bytes, pf: PreFilter) -> Optional[tuple]:
    """Extract (namespace, name) from a watch frame WITHOUT altering the
    frame bytes (the reference keeps raw bytes via a frame-capturing
    reader, pkg/authz/frames.go:13-68). Handles both JSON frames
    (newline-delimited WatchEvent documents) and kube-protobuf frames
    (4-byte length prefix + raw WatchEvent; reference negotiates the
    streaming serializer per content type, responsefilterer.go:557-626).

    The key space is defined by the PREFILTER's expressions: the grant
    side maps object ids through ``name_expr``/``namespace_expr``
    (run_prefilter_sync mapping), so the frame side must key identically — a prefilter
    with no namespace expression produces cluster-scoped ("", name) keys,
    and the frame's metadata.namespace must then be ignored rather than
    guessed from the resource name.

    FAIL CLOSED: raises :class:`~..proxy.kubeproto.ProtoError` for ANY
    frame carrying no judgeable object — truncated proto, unparseable
    JSON, rows without objects — and the join ends the stream rather
    than leaking bytes it cannot authorize. The only unjudged frames
    that pass are explicit progress/terminal markers (BOOKMARK, ERROR/
    Status) and bare whitespace keepalives."""
    stripped = frame.lstrip()
    if not stripped:
        return None  # newline keepalive: carries nothing
    if stripped[:1] != b"{" and len(frame) >= 4 and \
            int.from_bytes(frame[:4], "big") == len(frame) - 4:
        key = kubeproto.watch_frame_key(frame)  # may raise ProtoError
        if key is None:
            return None  # BOOKMARK / terminal Status: every consumer sees
        ns, name = key
        return (ns if pf.namespace_expr else "", name)
    try:
        ev = json.loads(frame)
        if ev.get("type") == "BOOKMARK":
            # bookmarks carry only a resourceVersion (no object to
            # authorize) and are progress markers every consumer may see:
            # pass through rather than keying on an empty name
            return None
        obj = ev.get("object") or {}
        if ev.get("type") == "ERROR" or obj.get("kind") == "Status":
            # a terminal Status (watch expiry, 410 Gone): no object to
            # judge, and suppressing it would leave the client hanging on
            # a dead watch instead of re-listing — pass it through (same
            # semantics as the proto path above)
            return None
        # Table-format watch events wrap rows (responsefilterer.go:667-677)
        if obj.get("kind") == "Table":
            rows = obj.get("rows") or []
            if rows:
                meta = (rows[0].get("object") or {}).get("metadata") or {}
            else:
                return None
        else:
            meta = obj.get("metadata") or {}
        ns = (meta.get("namespace") or "") if pf.namespace_expr else ""
        return (ns, meta.get("name") or "")
    except (ValueError, AttributeError, TypeError):
        # not JSON, not a well-formed proto frame, or JSON whose shape is
        # not a watch event (array/scalar top level, non-dict rows — a
        # broken aggregated-API backend): unjudgeable — fail closed with
        # the documented stream-ending error, never an unhandled crash
        raise kubeproto.ProtoError(
            "unparseable watch frame (truncated or unknown encoding)")
