"""Filtered watch: join the upstream watch stream with live permission
updates from the engine.

Mirrors the reference's dual-stream join
(/root/reference/pkg/authz/watch.go:27-111 and
responsefilterer.go:434-714): one side consumes relationship-update events
from the engine (the SpiceDB Watch API role) and recomputes the allowed
set — one device query for the WHOLE set per event batch, which also
catches grants/revocations mediated through arrows and usersets that the
reference's per-object re-checks of same-type events cannot see; the
other side decodes upstream watch frames, passing frames for allowed
objects through byte-identical, buffering the latest frame of
not-yet-allowed objects (flushed on an allow transition, dropped on
deny).

The engine side is poll-based (watch_since on the revisioned store log)
rather than a gRPC stream — same semantics, in-process.
"""

from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator, Optional

from ..engine import Engine
from ..rules.compile import PreFilter

from ..rules.input import ResolveInput
from ..proxy.types import ProxyRequest, ProxyResponse
from .lookups import AllowedSet, run_prefilter

# how often watches re-evaluate the allowed set when the schema uses
# expiring relationships (expiry emits no events; see filtered_watch)
EXPIRY_RECOMPUTE_INTERVAL = 1.0


async def filtered_watch(engine: Engine, upstream_resp: ProxyResponse,
                         pf: PreFilter, input: ResolveInput,
                         poll_interval: float = 0.05) -> ProxyResponse:
    """Wrap an upstream watch response with permission filtering."""
    if upstream_resp.status != 200 or upstream_resp.stream is None:
        return upstream_resp

    # Capture the revision BEFORE the prefilter snapshot: a grant landing
    # between the two is then re-checked by the event loop (idempotent)
    # instead of being lost. Running the prefilter eagerly (not inside the
    # streaming generator) also lets PreFilterError surface as a 500 before
    # the 200/chunked headers are committed. Engine calls go through
    # to_thread: a remote (tcp://) engine blocks on a socket.
    start_rev = await asyncio.to_thread(lambda: engine.revision)
    allowed = await run_prefilter(engine, pf, input)

    # The watch gate: (a) types whose writes can affect the watched
    # permission — event batches composed entirely of OTHER types skip
    # the allowed-set recompute (unrelated write traffic must not cost a
    # device query per watcher); (b) whether the schema can expire
    # grants — expiring tuples revoke at QUERY time with no event, so
    # such schemas get a periodic recompute tick (this also fixed a
    # pre-existing gap: expiry enforcement on watches silently depended
    # on unrelated write traffic arriving at all). Both the in-process
    # Engine and the tcp:// RemoteEngine expose watch_gate();
    # (None, True) = recompute on every batch + tick (the safe default).
    rel = pf.rel.generate(input)[0]
    gate = getattr(engine, "watch_gate", None)
    relevant, uses_expiration = (None, True)
    if gate is not None:
        relevant, uses_expiration = await asyncio.to_thread(
            gate, rel.resource_type, rel.resource_relation)
    expiry_interval = (EXPIRY_RECOMPUTE_INTERVAL if uses_expiration
                       else None)

    async def frames() -> AsyncIterator[bytes]:
        last_rev = start_rev
        last_recompute = asyncio.get_running_loop().time()
        buffered: dict[tuple, bytes] = {}
        frame_q: asyncio.Queue = asyncio.Queue()

        async def read_upstream():
            try:
                async for chunk in upstream_resp.stream:
                    frame_q.put_nowait(chunk)
            finally:
                frame_q.put_nowait(None)

        reader = asyncio.get_running_loop().create_task(read_upstream())
        try:
            while True:
                # 1) drain permission transitions from the engine log:
                # any event batch recomputes the FULL allowed set in one
                # device query, so grants/revocations mediated through
                # arrows and usersets (a namespace-level grant changing
                # pod visibility) move the stream too — per-id re-checks
                # of same-type events (the reference's model,
                # watch.go:48-109) cannot see those.
                events = await asyncio.to_thread(engine.watch_since,
                                                 last_rev)
                need = False
                if events:
                    last_rev = max(e.revision for e in events)
                    need = relevant is None or any(
                        e.relationship.resource_type in relevant
                        for e in events)
                now_t = asyncio.get_running_loop().time()
                if (not need and expiry_interval is not None
                        and now_t - last_recompute >= expiry_interval):
                    need = True  # expiring tuples revoke without events
                if need:
                    # strict=False: one unmappable id skips that id only —
                    # aborting the recompute would freeze the allowed set,
                    # which fails OPEN for revocations
                    fresh = await run_prefilter(engine, pf, input,
                                                strict=False)
                    last_recompute = now_t
                    for key in fresh.pairs - allowed.pairs:
                        frame = buffered.pop(key, None)
                        if frame is not None:
                            yield frame
                    for key in allowed.pairs - fresh.pairs:
                        buffered.pop(key, None)
                    allowed.pairs = fresh.pairs
                # 2) pass through / buffer upstream frames
                try:
                    frame = frame_q.get_nowait()
                    if frame is None:
                        return
                    key = _frame_object_key(frame, pf)
                    if key is None or allowed.allows(*key):
                        yield frame  # byte-identical passthrough
                    else:
                        buffered[key] = frame
                    continue  # drain frames eagerly before next poll
                except asyncio.QueueEmpty:
                    pass
                # idle: wait for a frame or the next poll tick
                try:
                    frame = await asyncio.wait_for(frame_q.get(),
                                                   timeout=poll_interval)
                    if frame is None:
                        return
                    key = _frame_object_key(frame, pf)
                    if key is None or allowed.allows(*key):
                        yield frame
                    else:
                        buffered[key] = frame
                except asyncio.TimeoutError:
                    continue
        finally:
            reader.cancel()

    return ProxyResponse(status=200, headers=dict(upstream_resp.headers),
                         stream=frames())


def _frame_object_key(frame: bytes, pf: PreFilter) -> Optional[tuple]:
    """Extract (namespace, name) from a watch frame WITHOUT altering the
    frame bytes (the reference keeps raw bytes via a frame-capturing
    reader, pkg/authz/frames.go:13-68).

    The key space is defined by the PREFILTER's expressions: the grant
    side maps object ids through ``name_expr``/``namespace_expr``
    (run_prefilter_sync mapping), so the frame side must key identically — a prefilter
    with no namespace expression produces cluster-scoped ("", name) keys,
    and the frame's metadata.namespace must then be ignored rather than
    guessed from the resource name."""
    try:
        ev = json.loads(frame)
        obj = ev.get("object") or {}
        # Table-format watch events wrap rows (responsefilterer.go:667-677)
        if obj.get("kind") == "Table":
            rows = obj.get("rows") or []
            if rows:
                meta = (rows[0].get("object") or {}).get("metadata") or {}
            else:
                return None
        else:
            meta = obj.get("metadata") or {}
        ns = (meta.get("namespace") or "") if pf.namespace_expr else ""
        return (ns, meta.get("name") or "")
    except ValueError:
        return None
