"""WatchHub: shared watch machinery for all watchers of one engine.

Two scaling problems with naive per-watcher watch loops (VERDICT r3 weak
#3/#5), solved here the way the reference's shared watch service does
(/root/reference/pkg/authz/watch.go:48-109, responsefilterer.go:509):

1. EVENT CONSUMPTION: one pump per engine instead of a 50 ms poll per
   watcher. In-process engines block on the store's revision condition
   (Engine.wait_events); ``tcp://`` engines ride a server-push
   subscription stream (RemoteEngine.watch_push_stream) — zero
   steady-state request traffic either way, and grant/revoke latency is
   bounded by the push, not a poll interval.

2. ALLOWED-SET RECOMPUTES: watchers whose prefilter resolves to the SAME
   relationship — and whose id→name mapping provably depends only on the
   looked-up resourceId (PreFilter.mapping_shareable) — form a GROUP;
   each relevant event batch triggers ONE device query per group, fanned
   out to every member. Device queries per write batch are O(distinct
   (rule, subject) pairs), not O(watchers).

Watchers receive items on a single per-watcher queue:
    ("pending", seq)         — a relevant event batch landed; a recompute
                               covering it is in flight. Watchers HOLD
                               upstream frames until the covering
                               ("allowed", ...) arrives, preserving the
                               ordering guarantee of the old per-watcher
                               loop (events applied BEFORE frames that
                               arrive after them — a revoked object's
                               frame must not slip through while the
                               recompute is still on the device).
    ("allowed", AllowedSet, seq) — a fresh full allowed set covering
                               every pending marker up to ``seq``
    ("error", exc)           — the shared computation failed; the watcher
                               should end its stream (client re-watches)
The type-relevance gate and the expiry tick (authz/watch.py semantics)
apply per group.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Optional

from ..rules.compile import PreFilter
from ..rules.input import ResolveInput
from .lookups import run_prefilter

log = logging.getLogger("sdbkp.watchhub")

# how often a group re-evaluates when its permission can expire (expiry
# emits no store events); mirrors authz/watch.py's historical constant
EXPIRY_RECOMPUTE_INTERVAL = 1.0

# fallback poll cadence for engines with neither wait_events nor a push
# stream (old remote hosts)
LEGACY_POLL_INTERVAL = 0.05

# fusing window for group recomputes (engine/batcher.py): one write batch
# kicks every always-relevant group within milliseconds of each other, so
# a short hold fuses N group fixpoints into ~N/8 device dispatches — the
# frames/s collapse at 50 groups was N dispatches per write batch.
# Wider than the request-path default: a recompute is background work
# whose result was already ordered by the ("pending", seq) marker, so a
# few ms of extra hold buys fusing even under to_thread scheduling jitter
RECOMPUTE_BATCH_WINDOW = 0.005


class WatcherHandle:
    """One registered watcher: the hub feeds ``queue``; the watch loop
    additionally feeds its own upstream frames into the same queue so it
    can sleep on a single ``get()``."""

    __slots__ = ("queue", "group", "reg_seq")

    def __init__(self, group: "_Group"):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.group = group
        # the group's trigger counter at registration: allowed sets
        # covering seq <= this may be OLDER than the watcher's own
        # initial prefilter snapshot (a recompute in flight across a
        # revocation or expiry) and are ignored; the watch start's
        # hub.refresh() guarantees a covering set with seq > reg_seq
        self.reg_seq = group.seq


class _Group:
    """Watchers sharing one (prefilter rule, resolved relationship)."""

    __slots__ = ("key", "pf", "input", "gate_types", "expiry_interval",
                 "watchers", "task", "seq", "last_recompute")

    def __init__(self, key, pf: PreFilter, input: ResolveInput,
                 gate_types: Optional[frozenset],
                 expiry_interval: Optional[float], now: float):
        self.key = key
        self.pf = pf
        self.input = input
        self.gate_types = gate_types
        self.expiry_interval = expiry_interval
        self.watchers: set = set()
        self.task: Optional[asyncio.Task] = None
        # monotone recompute-trigger counter: each relevant event batch
        # bumps it; a finished recompute covers every trigger at or below
        # the seq it started at (it reads the LATEST store state)
        self.seq = 0
        self.last_recompute = now


class WatchHub:
    """Owns the event pump and recompute groups for one engine. All
    methods run on the serving event loop."""

    def __init__(self, engine, poll_interval: float = LEGACY_POLL_INTERVAL):
        self.engine = engine
        self.poll_interval = poll_interval
        self._groups: dict = {}
        self._pump_task: Optional[asyncio.Task] = None
        self._source_task: Optional[asyncio.Task] = None
        self._push_stream = None
        self._q: Optional[asyncio.Queue] = None
        self._last_rev: Optional[int] = None
        # hub-owned LookupBatcher fusing concurrent group recomputes into
        # shared device fixpoints (in-process engines only; a tcp:// host
        # fuses server-side via --lookup-batch-window). Created lazily,
        # closed with the pump.
        self._recompute_batcher = None
        # register/unregister await (engine.revision, watch_gate) between
        # their check-then-set steps; without mutual exclusion two
        # concurrent registrations would duplicate pumps or overwrite each
        # other's groups (orphaning watchers from recomputes)
        self._reg_lock = asyncio.Lock()

    # -- registration --------------------------------------------------------

    async def register(self, pf: PreFilter,
                       input: ResolveInput) -> WatcherHandle:
        """Join (or form) the group for this watcher's resolved prefilter.
        The pump is anchored BEFORE returning, so events landing while the
        caller computes its initial allowed set are never lost — they only
        cause an idempotent recompute."""
        rel = pf.rel.generate(input)[0]
        async with self._reg_lock:
            if self._pump_task is None:
                self._last_rev = await asyncio.to_thread(
                    lambda: self.engine.revision)
                loop = asyncio.get_running_loop()
                self._q = asyncio.Queue()
                self._source_task = loop.create_task(self._source_reader())
                self._pump_task = loop.create_task(self._pump())
            if pf.mapping_shareable():
                key = (id(pf), rel.resource_type, rel.resource_relation,
                       rel.subject_type, rel.subject_id,
                       rel.subject_relation)
            else:
                key = object()  # mapping reads request state: never share
            group = self._groups.get(key)
            if group is None:
                gate = getattr(self.engine, "watch_gate", None)
                relevant, uses_expiration = (None, True)
                if gate is not None:
                    relevant, uses_expiration = await asyncio.to_thread(
                        gate, rel.resource_type, rel.resource_relation)
                group = _Group(
                    key, pf, input, relevant,
                    EXPIRY_RECOMPUTE_INTERVAL if uses_expiration else None,
                    asyncio.get_running_loop().time())
                self._groups[key] = group
                if self._q is not None:
                    # interrupt an in-flight queue wait: its timeout
                    # predates this group and may be far looser than its
                    # expiry tick
                    self._q.put_nowait(("wake", None))
            handle = WatcherHandle(group)
            group.watchers.add(handle)
            return handle

    async def refresh(self, handle: WatcherHandle) -> None:
        """Force one ordered recompute for the handle's group: bumps the
        trigger counter (so members hold frames until it lands) and
        kicks. Watch starts call this right after registering — it closes
        any event gap between the caller's initial prefilter snapshot and
        its registration, and guarantees the first applied set is newer
        than reg_seq (tick recomputes in flight across registration are
        ignored by the strict staleness guard)."""
        group = handle.group
        group.seq += 1
        for w in list(group.watchers):
            w.queue.put_nowait(("pending", group.seq))
        self._kick(group)

    async def unregister(self, handle: WatcherHandle) -> None:
        async with self._reg_lock:
            group = handle.group
            group.watchers.discard(handle)
            if not group.watchers:
                self._groups.pop(group.key, None)
                if group.task is not None:
                    group.task.cancel()
            if not self._groups and self._pump_task is not None:
                await self._stop_pump_locked()

    async def _stop_pump_locked(self) -> None:
        """Cancel and null all pump state (caller holds _reg_lock)."""
        if self._pump_task is not None:
            self._pump_task.cancel()
            self._pump_task = None
        if self._source_task is not None:
            self._source_task.cancel()
            self._source_task = None
        if self._push_stream is not None:
            # closing the socket unblocks the in-flight recv
            await asyncio.to_thread(self._push_stream.close)
            self._push_stream = None
        store = getattr(self.engine, "store", None)
        if hasattr(store, "wake_waiters"):
            # release any worker thread parked in wait_since so loop
            # shutdown never waits out the wait timeout
            store.wake_waiters()
        if self._recompute_batcher is not None:
            # flush + mark dead: a recompute racing the teardown falls
            # through to the direct engine path (batcher.close contract)
            self._recompute_batcher.close()
            self._recompute_batcher = None
        self._q = None

    async def _teardown_pump(self, dead_pump: asyncio.Task) -> None:
        """Post-failure cleanup, scheduled by a dying pump: reset state so
        register() can start fresh, and — if watchers remain or arrived in
        the gap — restart the pump for them after a short backoff (an
        engine host outage must not become a tight reconnect loop)."""
        await asyncio.sleep(1.0)
        async with self._reg_lock:
            if self._pump_task is not dead_pump:
                return  # someone already cleaned up / restarted
            await self._stop_pump_locked()
            if self._groups:
                self._last_rev = await asyncio.to_thread(
                    lambda: self.engine.revision)
                loop = asyncio.get_running_loop()
                self._q = asyncio.Queue()
                self._source_task = loop.create_task(self._source_reader())
                self._pump_task = loop.create_task(self._pump())

    # -- event pump ----------------------------------------------------------

    def _wait_timeout(self) -> float:
        """How long the blocking event wait may sleep: bounded by half the
        tightest expiry interval so expiring grants still tick."""
        intervals = [g.expiry_interval for g in self._groups.values()
                     if g.expiry_interval]
        return min(intervals) / 2 if intervals else 2.0

    # bound on any single blocking wait inside the source reader, so a
    # shutdown that misses the wake never stalls longer than this
    SOURCE_WAIT = 5.0

    async def _source_reader(self) -> None:
        """Dedicated event consumer feeding ``self._q``: server-push
        stream for remote engines > the store's revision condition
        in-process > legacy watch_since polling. Owning the source in ONE
        task means the pump can time out its queue wait freely (for
        expiry ticks and registration wakes) without ever leaving two
        readers on one stream."""
        eng, q = self.engine, self._q
        try:
            stream = None
            if hasattr(eng, "watch_push_stream"):
                # the connect runs in a worker thread that outlives a task
                # cancellation; park the stream in a holder the moment it
                # exists so exactly one side (the thread, or the cancel
                # handler below) closes it — otherwise a cancel mid-connect
                # leaks the dedicated socket until GC
                holder: dict = {}
                cancelled = threading.Event()

                def _connect():
                    s = eng.watch_push_stream(self._last_rev)
                    holder["stream"] = s
                    if cancelled.is_set():
                        late = holder.pop("stream", None)
                        if late is not None:
                            try:
                                late.close()
                            except Exception:  # noqa: BLE001
                                pass
                    return s

                try:
                    stream = await asyncio.to_thread(_connect)
                except asyncio.CancelledError:
                    cancelled.set()
                    orphan = holder.pop("stream", None)
                    if orphan is not None:
                        try:
                            orphan.close()
                        except Exception:  # noqa: BLE001
                            pass
                    raise
                except Exception as e:
                    # an engine host predating the watch_subscribe op (or
                    # a flaky connect): fall back to polling rather than
                    # erroring every watcher in a re-watch loop
                    log.info("watch push subscribe unavailable (%s); "
                             "falling back to polling", e)
            if stream is not None:
                self._push_stream = stream
                while True:
                    events = await asyncio.to_thread(stream.next_batch)
                    if events:
                        q.put_nowait(("events", events))
            elif hasattr(eng, "wait_events"):
                rev = self._last_rev
                while True:
                    events = await asyncio.to_thread(
                        eng.wait_events, rev, self.SOURCE_WAIT)
                    if events:
                        rev = max(e.revision for e in events)
                        q.put_nowait(("events", events))
            else:
                rev = self._last_rev
                while True:
                    events = await asyncio.to_thread(eng.watch_since, rev)
                    if events:
                        rev = max(e.revision for e in events)
                        q.put_nowait(("events", events))
                    else:
                        await asyncio.sleep(self.poll_interval)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            q.put_nowait(("error", e))

    async def _next_events(self):
        """One item from the source queue, bounded by the expiry-tick
        deadline (timeout / "wake" -> [] so the pump re-evaluates its
        groups)."""
        try:
            item = await asyncio.wait_for(self._q.get(),
                                          timeout=self._wait_timeout())
        except asyncio.TimeoutError:
            return []
        if item[0] == "error":
            raise item[1]
        if item[0] == "wake":
            return []
        return item[1]

    async def _pump(self) -> None:
        try:
            while True:
                try:
                    events = await self._next_events()
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # trimmed history / dead engine host: every watcher
                    # ends its stream (clients re-list + re-watch, kube
                    # "resourceVersion too old" semantics). Tear the pump
                    # state down HERE — leaving _pump_task set would stop
                    # register() from ever starting a fresh pump, silently
                    # freezing every future watcher's allowed set
                    log.warning("watch pump ending: %s", e)
                    for g in list(self._groups.values()):
                        for w in list(g.watchers):
                            w.queue.put_nowait(("error", e))
                    asyncio.get_running_loop().create_task(
                        self._teardown_pump(asyncio.current_task()))
                    return
                if events:
                    self._last_rev = max(e.revision for e in events)
                now = asyncio.get_running_loop().time()
                for g in list(self._groups.values()):
                    if bool(events) and (
                            g.gate_types is None
                            or any(e.relationship.resource_type
                                   in g.gate_types for e in events)):
                        # event-batch trigger: frames arriving after the
                        # batch must be judged post-batch, so watchers get
                        # an ordering marker
                        g.seq += 1
                        for w in list(g.watchers):
                            w.queue.put_nowait(("pending", g.seq))
                        self._kick(g)
                    elif g.expiry_interval is not None \
                            and g.task is None \
                            and now - g.last_recompute >= g.expiry_interval:
                        # expiry tick: no event happened, so there is no
                        # frame ordering to protect — just refresh. The
                        # task-is-None check stops a slow recompute (first
                        # compile) from stacking re-triggers behind itself.
                        g.last_recompute = now
                        self._kick(g)
        except asyncio.CancelledError:
            pass

    def _kick(self, group: _Group) -> None:
        """Schedule ONE recompute for the group; triggers landing while
        one is in flight collapse into at most one follow-up run (the
        recompute reads the latest store state)."""
        if group.task is None:
            group.task = asyncio.get_running_loop().create_task(
                self._recompute(group))

    def _recompute_lookup(self):
        """``lookup(rel) -> [ids]`` override for run_prefilter, routing
        group recomputes through a hub-owned LookupBatcher so the N
        groups one write batch triggers fuse into ~N/8 device fixpoints
        instead of N independent dispatches. None when the engine cannot
        batch locally (remote client — the engine HOST fuses across all
        proxies with --lookup-batch-window) or already batches every
        lookup itself (engine._batcher set: the request-path batcher
        would fuse our recomputes with live list prefilters, strictly
        better)."""
        eng = self.engine
        if not hasattr(eng, "_lookup_direct") \
                or getattr(eng, "_batcher", None) is not None:
            return None
        if self._recompute_batcher is None:
            from ..engine.batcher import LookupBatcher

            self._recompute_batcher = LookupBatcher(
                eng, window=RECOMPUTE_BATCH_WINDOW, max_rows=8)
        batcher = self._recompute_batcher

        def lookup(rel):
            from ..engine.engine import mask_to_ids

            fut = batcher.submit(
                rel.resource_type, rel.resource_relation,
                rel.subject_type, rel.subject_id,
                rel.subject_relation or None)
            mask, interner = fut.result()
            return mask_to_ids(mask, interner)

        return lookup

    async def _recompute(self, group: _Group) -> None:
        import time as _time

        from ..utils.metrics import metrics

        try:
            while True:
                start_seq = group.seq
                t0 = _time.perf_counter()
                try:
                    fresh = await run_prefilter(
                        self.engine, group.pf, group.input, strict=False,
                        lookup=self._recompute_lookup())
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for w in list(group.watchers):
                        w.queue.put_nowait(("error", e))
                    return
                # per-group recompute latency: the watch path's engine
                # stage (there is no request trace to span — recomputes
                # are write-triggered background work fanned out to
                # every watcher of the group)
                metrics.histogram("watchhub_recompute_seconds").observe(
                    _time.perf_counter() - t0)
                group.last_recompute = asyncio.get_running_loop().time()
                for w in list(group.watchers):
                    w.queue.put_nowait(("allowed", fresh, start_seq))
                if group.seq == start_seq:
                    return
        finally:
            group.task = None
