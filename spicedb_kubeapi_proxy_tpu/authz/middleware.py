"""The per-request authorization orchestrator.

Mirrors /root/reference/pkg/authz/authz.go:23-194 (WithAuthorization):

1. build ResolveInput from the authenticated request
2. always-allow API discovery (GET /api, /apis, /openapi, /version —
   authz.go:205-207)
3. match rules on (verb, group, version, resource); none -> 403
4. filter rules by their `if` conditions; none left -> 403
5. run every matching rule's checks as ONE bulk engine query; any
   denial -> 403
6. dispatch:
   - write verbs with an update rule -> durable dual-write workflow
     (≤30s wait), response written from the workflow's KubeResp
   - watch with a prefilter -> filtered watch join
   - list/get with a prefilter -> prefilter overlapped with the upstream
     request, response filtered (lists/tables/single object)
   - list with postfilters -> upstream response recorded and bulk-checked
   - get with postchecks -> checks run after a 2xx upstream response
   - otherwise -> plain reverse proxy
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from ..dtx.runner import ActivityError, WorkflowEngine, WorkflowTimeout
from ..dtx.workflow import KubeResp, LOCK_MODE_PESSIMISTIC
from ..engine import Engine
from ..engine.remote import EngineInternalError
from ..obs.trace import tracer
from ..proxy.types import ProxyRequest, ProxyResponse, kube_status
from ..utils.metrics import metrics
from ..utils.resilience import DependencyUnavailable
from ..rules.expr import ExprError
from ..rules.input import ResolveInput, UserInfo
from ..rules.matcher import MapMatcher, RequestMeta
from .check import cached_verdict, run_checks
from .filterer import apply_filter
from .lookups import PreFilterError, run_prefilter, single_prefilter
from .postfilter import filter_list_response
from .update import UpdateError, build_workflow_input, single_update_rule
from .watch import filtered_watch

WRITE_VERBS = ("create", "update", "patch", "delete")

ALWAYS_ALLOWED_PREFIXES = ("/api", "/apis", "/openapi", "/version")

WORKFLOW_RESULT_TIMEOUT = 30.0  # reference DefaultWorkflowTimeout

# every fail-closed 503 carries Retry-After in [1, this] seconds: the
# sources (breaker reset windows, admission drain estimates, shard
# partial-shed maxima, overlay fold estimates, leaderless elections)
# each bound their own hint, but the cap holds even if a future source
# forgets — an unbounded Retry-After parks polite clients forever, the
# availability failure mode the chaos invariants treat as fail-open
RETRY_AFTER_CAP_S = 60


@dataclass
class AuthzDeps:
    matcher: MapMatcher
    engine: Engine
    upstream: object  # Upstream callable
    workflow: Optional[WorkflowEngine] = None
    default_lock_mode: str = LOCK_MODE_PESSIMISTIC
    watch_poll_interval: float = 0.05
    # shared watch machinery (one event pump per engine, one allowed-set
    # recompute per (rule, subject) group); created lazily on first watch
    watch_hub: Optional[object] = None
    # TTL/disk cache for the always-allowed discovery paths (reference
    # disk-cached discovery RESTMapper, server.go:228-243); None = every
    # discovery request hits the upstream
    discovery_cache: Optional[object] = None
    # per-dependency circuit breakers (utils/resilience.CircuitBreaker)
    # whose open state makes /readyz report unready with a reason
    breakers: tuple = ()
    # admission controller (admission/controller.py): every engine-bound
    # request acquires a cost-classed, per-tenant fair-queue slot before
    # the check phase; None = unguarded (today's behavior)
    admission: Optional[object] = None
    # decision audit log (obs/audit.py AuditLog): one JSON line per
    # authorization verdict — denies always, allows rate-capped;
    # None = no audit (today's behavior)
    audit: Optional[object] = None
    # request caveat context (caveats/): when enabled, every engine-bound
    # phase carries the caller's attributes (client IP from the trusted
    # header below, user name/groups, verb/resource) so conditional
    # grants — IP allowlists, attribute gates — resolve on-device;
    # missing context fails closed at the engine
    caveat_context_enabled: bool = True
    # the header the proxy trusts for the client IP (set by your LB /
    # ingress; the LAST hop of a comma-separated X-Forwarded-For — the
    # one the trusted proxy appended)
    caveat_ip_header: str = "x-forwarded-for"


def request_caveat_context(info, user, headers: dict,
                           ip_header: str = "x-forwarded-for") -> dict:
    """The request's caveat-context dict: what a caveat expression can
    see about the CALLER. Keys are caveat parameter names (SpiceDB
    passes request context the same way); the engine auto-injects the
    dispatch clock as ``now``. The client IP comes only from the
    configured trusted header — never from unauthenticated ones."""
    ctx: dict = {
        "user": (user.name if user else "") or "",
        "groups": list(user.groups) if user and user.groups else [],
        "verb": info.verb,
        "resource": info.resource,
        "namespace": info.namespace,
        "name": info.name,
    }
    raw = ""
    want = ip_header.lower()
    for k, v in (headers or {}).items():
        if k.lower() == want:
            raw = v
            break
    if raw:
        # LAST hop of a comma-separated chain: standard LBs/ingresses
        # APPEND the address they verified to whatever the client sent,
        # so earlier entries are attacker-controlled — trusting the
        # first hop would let any caller spoof an allowlisted IP with a
        # forged header. (Single-value headers the ingress overwrites,
        # e.g. x-real-ip, are unaffected.) Tolerate a :port suffix.
        hop = raw.split(",")[-1].strip()
        if hop.count(":") == 1 and "." in hop:
            hop = hop.split(":")[0]
        if hop:
            ctx["ip"] = hop
    return ctx


def _audit(deps: AuthzDeps, info, user, *, allow: bool,
           rules=None, reason: str = "",
           cache_hit: Optional[bool] = None) -> None:
    """Write one decision line when auditing is on; never raises into
    the authorization chain (a broken audit sink must not deny or allow
    anything)."""
    a = deps.audit
    if a is None:
        return
    rev = getattr(getattr(deps.engine, "store", None), "revision", None)
    try:
        a.decision(
            allow=allow,
            verb=info.verb,
            resource=info.resource,
            subresource=info.subresource,
            namespace=info.namespace,
            name=info.name,
            subject=(user.name if user else ""),
            groups=(user.groups if user else None),
            rule=(",".join(r.name for r in rules) if rules else None),
            reason=reason,
            cache_hit=cache_hit,
            revision=rev if isinstance(rev, int) else None,
            trace_id=tracer.current_trace_id(),
            stages_us=tracer.stage_micros(),
        )
    except Exception:  # noqa: BLE001 - audit must never break serving
        metrics.counter("audit_write_errors_total").inc()


async def _traced_upstream(deps: AuthzDeps, req: ProxyRequest
                           ) -> ProxyResponse:
    """The ONE upstream call site wrapper: times the kube-apiserver RTT
    as a named child span + histogram and forwards the trace context as
    a W3C ``traceparent`` header so the upstream's own telemetry can
    stitch to ours."""
    t0 = time.perf_counter()
    with tracer.span("upstream") as sp:
        tp = sp.traceparent()
        if tp is not None:
            req.headers = {k: v for k, v in req.headers.items()
                           if k.lower() != "traceparent"}
            req.headers["traceparent"] = tp
        resp = await deps.upstream(req)
        sp.set("status", resp.status)
    metrics.histogram("proxy_upstream_seconds").observe(
        time.perf_counter() - t0)
    return resp


def _always_allowed(req: ProxyRequest) -> bool:
    """API discovery & metadata requests pass through unfiltered
    (authz.go:205-207 allows get on /api, /apis, /openapi/v2)."""
    info = req.request_info
    if info is None:
        return False
    return (not info.is_resource_request
            and info.verb == "get"
            and info.path.startswith(ALWAYS_ALLOWED_PREFIXES))


async def authorize(req: ProxyRequest, deps: AuthzDeps) -> ProxyResponse:
    """The authorization chain, with fail-closed dependency degradation:
    an open circuit breaker, an exhausted deadline, or an engine host
    mid-leader-failover (``NotLeaderError`` / no reachable leader, both
    in the DependencyUnavailable family) — upstream kube or the remote
    TPU engine — maps to a bounded, RETRYABLE kube Status 503 with a
    ``Retry-After`` header. Never a hang (deadlines bound every
    dependency wait) and never a fail-open 200 OR a stale verdict (an
    unanswerable check is a denial-shaped error, mirroring how SpiceDB
    failures surface as retryable statuses in dtx/workflow.py
    kube_conflict_resp; a deposed engine's answers are refused at the
    source by term fencing, parallel/failover.py)."""
    try:
        return await _authorize_inner(req, deps)
    except EngineInternalError as exc:
        # a remote engine host ANSWERED kind="internal" (an exception
        # inside its op handler, including chaos-armed server-side
        # faults). Not a transport failure, so breakers rightly stay
        # closed — but from this request's view the dependency failed:
        # surface the same bounded, RETRYABLE fail-closed 503 as every
        # other dependency failure, not a raw 500 panic (the chaos
        # campaign's never-fail-open invariant requires
        # deny/403/503-with-Retry-After for every injected fault; a
        # 500 with no Retry-After strands polite clients). Scoped to
        # the INTERNAL kind only: auth/proto/frame errors are
        # permanent misconfigurations and must stay loud, not become
        # endlessly-retried "transient" 503s.
        e = DependencyUnavailable("engine-internal", str(exc),
                                  retry_after=1.0)
        tracer.flag("error", str(e))
        return _fail_closed_503(e)
    except DependencyUnavailable as e:
        from ..admission import AdmissionRejected

        # tail sampling always keeps these: a shed (the admission design
        # working) is flagged "shed" — and ONLY shed, so error-trace
        # filters see real failures — while every other fail-closed 503
        # (breaker open, deadline, leaderless engine) flags "error"
        if isinstance(e, AdmissionRejected):
            tracer.flag("shed")
            # sheds never reach a verdict, so the decision audit would
            # otherwise disagree with the trace ring about this request
            # ever existing: emit the rate-capped shed line here, the
            # ONE place every admission rejection funnels through
            if deps.audit is not None:
                try:
                    deps.audit.shed(
                        op_class=e.op_class,
                        tenant=(req.user.name if req.user else ""),
                        verb=(req.request_info.verb
                              if req.request_info else req.method),
                        resource=(req.request_info.resource
                                  if req.request_info else ""),
                        retry_after=e.retry_after,
                        reason=e.reason,
                        trace_id=tracer.current_trace_id())
                except Exception:  # noqa: BLE001 - audit never gates
                    metrics.counter("audit_write_errors_total").inc()
        else:
            tracer.flag("error", str(e))
        return _fail_closed_503(e)


def _fail_closed_503(e: DependencyUnavailable) -> ProxyResponse:
    """The ONE construction of the fail-closed 503: counted per
    dependency, Retry-After clamped to [1, RETRY_AFTER_CAP_S], and
    trace-stamped — every DependencyUnavailable source (and the
    engine-internal wrapper above) funnels through here so a new header
    or a cap change can never miss a branch."""
    metrics.counter("proxy_dependency_unavailable_total",
                    dependency=e.dependency).inc()
    resp = kube_status(
        503, f"dependency {e.dependency} unavailable: {e}",
        "ServiceUnavailable")
    retry_after = e.retry_after if isinstance(
        e.retry_after, (int, float)) else 1.0
    resp.headers["Retry-After"] = str(
        min(RETRY_AFTER_CAP_S, max(1, int(retry_after + 0.5))))
    # these early rejects return BEFORE the root span's normal finish
    # path stamps headers, and some callers (in-memory transports,
    # tests) invoke authorize() without the server's root-span
    # wrapper at all — stamp the trace id HERE so a shed/breaker 503
    # is always followable from the client into /debug/traces
    # (server.handle's setdefault then keeps this value)
    trace_id = tracer.current_trace_id()
    if trace_id is not None:
        resp.headers.setdefault("X-Trace-Id", trace_id)
    return resp


async def _authorize_inner(req: ProxyRequest,
                           deps: AuthzDeps) -> ProxyResponse:
    info = req.request_info
    user = req.user
    if info is None:
        return kube_status(500, "no request info")
    if user is None:
        return kube_status(401, "no user info")

    if _always_allowed(req):
        if deps.discovery_cache is not None:
            return await deps.discovery_cache.serve(req, deps.upstream)
        return await _traced_upstream(deps, req)

    input = ResolveInput.create(info, user, body=req.body or None,
                                headers=req.headers)

    with tracer.span("rule_match") as sp:
        rules = deps.matcher.match(RequestMeta.from_request(info))
        if not rules:
            sp.set("matched", 0)
            _audit(deps, info, user, allow=False,
                   reason="no rule matches the request")
            return kube_status(
                403,
                f"user {user.name!r} cannot {info.verb} {info.resource}",
                "Forbidden")
        try:
            rules = [r for r in rules if r.conditions_pass(input)]
        except ExprError as e:
            return kube_status(500, f"evaluating rule conditions: {e}")
        sp.set("matched", len(rules))
        if not rules:
            _audit(deps, info, user, allow=False,
                   reason="every matching rule's conditions filtered out")
            return kube_status(
                403,
                f"user {user.name!r} cannot {info.verb} {info.resource}",
                "Forbidden")

    # -- request caveat context: the caller attributes conditional grants
    # evaluate against (client IP, user, verb...), extracted ONCE and
    # carried by every engine-bound phase of this request. None when
    # disabled — caveats needing request context then fail closed.
    caveat_ctx = (request_caveat_context(info, user, req.headers,
                                         deps.caveat_ip_header)
                  if deps.caveat_context_enabled else None)

    # -- admission control (admission/): the request is about to touch the
    # engine — acquire a cost-classed slot under the caller's tenant
    # identity FIRST, so one subject's LookupResources storm queues behind
    # its own fair share instead of starving everyone's checks. A shed or
    # timed-out wait raises AdmissionRejected (DependencyUnavailable), and
    # authorize() above turns it into the fail-closed 503 + Retry-After —
    # before any check dispatch, workflow enqueue, or upstream byte.
    if deps.admission is None:
        return await _authorized(req, deps, info, user, input, rules,
                                 caveat_ctx=caveat_ctx)
    from ..admission import classify_request

    with tracer.span("admission_wait") as sp:
        cls = classify_request(info.verb, rules)
        sp.set("class", cls.name)
        # scale-out (scaleout/planner.py): a scatter op touches every
        # shard group, so it is charged once per touched shard — the
        # planner reports the fanout, single-engine deployments have no
        # admission_fanout and stay at 1x
        fanout_of = getattr(deps.engine, "admission_fanout", None)
        if fanout_of is not None:
            fanout = fanout_of(cls)
            if fanout > 1:
                sp.set("shards", fanout)
                cls = cls.scaled(fanout)
        ticket = await deps.admission.acquire_async(
            user.name or "system:anonymous", cls)
    try:
        return await _authorized(req, deps, info, user, input, rules,
                                 ticket, caveat_ctx=caveat_ctx)
    finally:
        # backstop for the paths whose engine work OVERLAPS or FOLLOWS
        # the upstream call (prefilter, postfilter, postchecks): they
        # hold the ticket to here, so their span includes an upstream
        # RTT — the weighted COST accounting is correct (the engine was
        # genuinely busy for part of it) but the duration is not an
        # engine-latency sample, so it must not feed the limiter (one
        # 100ms kube RTT against a ~1ms check baseline would read as
        # massive engine congestion). Engine-only spans released early
        # inside _authorized DO observe; release is idempotent.
        ticket.release(observe=False)


async def _authorized(req: ProxyRequest, deps: AuthzDeps, info, user,
                      input: ResolveInput, rules,
                      ticket=None, caveat_ctx=None) -> ProxyResponse:
    """The engine-bound phases (checks onward). The admission ticket,
    when admission is enabled, is held from the check phase until the
    last engine-bound segment of the request: it is released before
    upstream-dominated tails (a plain proxied read/write, the dual-write
    workflow wait) — holding it there would bill kube-apiserver latency
    to the engine limiter and convert an upstream slowdown into engine
    unavailability. Paths whose engine work OVERLAPS or FOLLOWS the
    upstream call (prefilter, postfilter, postchecks) hold it across."""
    try:
        # non-blocking decision-cache probe first: a full hit answers on
        # the event loop with zero thread handoff (the repeat-heavy
        # serving shape — same rule set, same subject — pays only dict
        # lookups); any miss falls to the to_thread path, which keeps the
        # loop free while the device query's readback is in flight
        # (concurrent requests pipeline their dispatches; the reference
        # fans checks out over goroutines, check.go:77-93)
        with tracer.span("cache_probe") as sp:
            items, verdict = cached_verdict(deps.engine, rules, input,
                                            context=caveat_ctx)
            sp.set("hit", verdict is not None)
        # a fully-cached verdict means this span dispatched NOTHING: its
        # (floor-clamped) duration must not feed the limiter's baseline,
        # or repeat-heavy cache-hit traffic would pin the baseline at the
        # floor and make ordinary device latency read as congestion
        engine_sampled = verdict is None
        if verdict is None:
            with tracer.span("engine_dispatch", items=len(items)):
                verdict = await asyncio.to_thread(
                    run_checks, deps.engine, rules, input, items=items,
                    context=caveat_ctx)
        if not verdict:
            _audit(deps, info, user, allow=False, rules=rules,
                   reason="check denied", cache_hit=not engine_sampled)
            return kube_status(
                403,
                f"user {user.name!r} is not permitted to {info.verb} "
                f"{info.resource} {input.namespaced_name}",
                "Forbidden")
        if not (info.verb == "get" and any(r.post_checks for r in rules)):
            # gets with postchecks aren't decided yet — their audit line
            # is written after the post-upstream checks below
            _audit(deps, info, user, allow=True, rules=rules,
                   reason="checks passed", cache_hit=not engine_sampled)
    except ExprError as e:
        return kube_status(500, f"resolving checks: {e}")

    # -- write path: durable dual-write --------------------------------------
    if info.verb in WRITE_VERBS:
        try:
            update_rule = single_update_rule(rules)
        except UpdateError as e:
            return kube_status(500, str(e))
        if update_rule is not None:
            # fail fast with the 503 + Retry-After family BEFORE durably
            # enqueueing the dual-write when a dependency circuit is
            # hard-open: a BreakerOpen raised inside a workflow activity
            # would be stringified into an ActivityError 502 after
            # burning the workflow's whole retry budget against instant
            # rejections (check_open never consumes the probe slot)
            for b in deps.breakers:
                b.check_open()
            if ticket is not None:
                # the engine-bound part (the admission check) is done;
                # the ≤30s workflow wait is upstream + sqlite time (its
                # own engine writes are gated host-side when remote)
                ticket.release(observe=engine_sampled)
            return await _dual_write(req, deps, update_rule, input)
        if ticket is not None:
            # plain proxied write: no engine work left
            ticket.release(observe=engine_sampled)
        return await _traced_upstream(deps, req)

    # -- watch ----------------------------------------------------------------
    try:
        pf = single_prefilter(rules)
    except PreFilterError as e:
        return kube_status(500, str(e))

    if info.verb == "watch":
        if pf is None:
            if ticket is not None:
                # plain proxied watch: checks are done
                ticket.release(observe=engine_sampled)
            return await _traced_upstream(deps, req)
        if deps.watch_hub is None:
            from .watchhub import WatchHub

            deps.watch_hub = WatchHub(
                deps.engine, poll_interval=deps.watch_poll_interval)
        try:
            upstream_resp = await _traced_upstream(deps, req)
            with tracer.span("watch_join"):
                # establishment only: the trace covers joining the hub
                # and computing the initial allowed set, never the
                # long-lived stream itself
                return await filtered_watch(
                    deps.engine, upstream_resp, pf[1], input,
                    poll_interval=deps.watch_poll_interval,
                    hub=deps.watch_hub)
        except (PreFilterError, ExprError) as e:
            return kube_status(500, f"watch prefilter: {e}")

    # -- read path: prefilter overlap + response filtering --------------------
    post_filters = [p for r in rules for p in r.post_filters]
    # the ONE derivation of which engine-bound tails this request has:
    # the dispatch branches below AND the early-release decision both
    # read these, so a new tail cannot silently escape the admission span
    run_postfilter = bool(post_filters and info.verb == "list")
    run_postchecks = (info.verb == "get"
                      and any(r.post_checks for r in rules))
    prefilter_task = None
    if pf is not None:
        async def _traced_prefilter():
            # ensure_future copies the contextvar context, so the span
            # lands on this request's trace even though the prefilter
            # runs concurrently with the upstream round trip
            with tracer.span("prefilter"):
                return await run_prefilter(deps.engine, pf[1], input,
                                           context=caveat_ctx)

        prefilter_task = asyncio.ensure_future(_traced_prefilter())
    if ticket is not None and prefilter_task is None \
            and not run_postfilter and not run_postchecks:
        # nothing engine-bound overlaps or follows the upstream call:
        # release now so the upstream RTT isn't billed as engine latency
        # (and a fully-cached span isn't billed as an engine sample)
        ticket.release(observe=engine_sampled)
    if run_postfilter:
        # the postfilter resolves rule expressions over each item's JSON
        # object — protobuf list bodies can't feed it, so strip non-JSON
        # ranges from the Accept (keeping JSON ;as=Table form: the
        # postfilter handles Tables). Prefilter paths negotiate protobuf
        # fine (authz/filterer.py).
        from ..proxy.upstream import rewrite_accept

        accept = next((v for k, v in req.headers.items()
                       if k.lower() == "accept"), "")
        req.headers = {k: v for k, v in req.headers.items()
                       if k.lower() != "accept"}
        req.headers["Accept"] = rewrite_accept(accept, watching=False,
                                               json_only=True)
    try:
        resp = await _traced_upstream(deps, req)
    except Exception:
        if prefilter_task:
            prefilter_task.cancel()
        raise
    if prefilter_task is not None:
        try:
            # reference waits ≤10s for the concurrent prefilter
            # (responsefilterer.go:44,196-204)
            allowed = await asyncio.wait_for(prefilter_task, timeout=10.0)
        except asyncio.TimeoutError:
            return kube_status(401, "prefilter timed out")
        except (PreFilterError, ExprError) as e:
            return kube_status(401, f"prefilter: {e}")
        resp = apply_filter(resp, allowed, input)
    if run_postfilter:
        try:
            with tracer.span("postfilter"):
                resp = await asyncio.to_thread(
                    filter_list_response, deps.engine, post_filters,
                    input, resp, caveat_ctx)
        except ExprError as e:
            return kube_status(401, f"postfilter: {e}")

    # -- postchecks (get only; reference shouldRunPostChecks authz.go:211-220)
    if run_postchecks and resp.status >= 300:
        # the deferred audit line (checks passed, allow withheld above)
        # must still be written: the subject WAS allowed through to the
        # upstream, whose error skips the postchecks entirely
        _audit(deps, info, user, allow=True, rules=rules,
               reason=f"checks passed (upstream {resp.status}, "
                      "postchecks skipped)")
    if run_postchecks and resp.status < 300:
        try:
            with tracer.span("postcheck"):
                post_items, post_verdict = cached_verdict(
                    deps.engine, rules, input, post=True,
                    context=caveat_ctx)
                post_cached = post_verdict is not None
                if post_verdict is None:
                    post_verdict = await asyncio.to_thread(
                        run_checks, deps.engine, rules, input, post=True,
                        items=post_items, context=caveat_ctx)
            _audit(deps, info, user, allow=bool(post_verdict),
                   rules=rules,
                   reason=("postchecks passed" if post_verdict
                           else "postcheck denied"),
                   cache_hit=post_cached)
            if not post_verdict:
                return kube_status(
                    403,
                    f"user {user.name!r} is not permitted to {info.verb} "
                    f"{info.resource} {input.namespaced_name}",
                    "Forbidden")
        except ExprError as e:
            return kube_status(500, f"resolving postchecks: {e}")
    return resp


async def _dual_write(req: ProxyRequest, deps: AuthzDeps, rule,
                      input: ResolveInput) -> ProxyResponse:
    """Launch the workflow and wait ≤30s (reference performUpdate/dualWrite,
    update.go:53-195)."""
    if deps.workflow is None:
        return kube_status(500, "no workflow engine configured")
    try:
        wf_input = build_workflow_input(rule, input, req.uri, req.headers)
    except (UpdateError, ExprError) as e:
        return kube_status(500, f"resolving update: {e}")
    mode = rule.locking or deps.default_lock_mode
    with tracer.span("dual_write", mode=mode):
        iid = await deps.workflow.create_instance(mode, wf_input.to_dict())
        try:
            out = await deps.workflow.get_result(
                iid, timeout=WORKFLOW_RESULT_TIMEOUT)
        except WorkflowTimeout:
            return kube_status(504, "dual-write timed out")
        except ActivityError as e:
            return kube_status(502, f"dual-write failed: {e}")
    resp = KubeResp.from_activity(out)
    headers = dict(resp.headers)
    headers["Content-Length"] = str(len(resp.body))
    return ProxyResponse(status=resp.status, headers=headers, body=resp.body)
