"""Parser + IR for the Zanzibar-style schema DSL.

The reference embeds a full SpiceDB server and feeds it schemas written in
the SpiceDB schema language (/root/reference/pkg/spicedb/bootstrap.yaml:1-38).
This module implements the subset of that language the proxy's behavior
depends on, as a small hand-rolled tokenizer + recursive-descent parser
producing a typed IR that the TPU compiler (ops/reachability.py) consumes.

Supported surface:

    use expiration

    definition ns/name {
        relation viewer: user | group#member | user:* | user with expiration
        permission view = viewer + editor
        permission edit = (a & b) - c
        permission via = parent->view
        permission none = nil
    }

Operator precedence follows SpiceDB: ``-`` and ``&`` and ``+`` are
left-associative at the same precedence level; parenthesize to mix safely.
Arrows bind tighter than binary operators.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional

log = logging.getLogger("sdbkp.schema")


class SchemaError(ValueError):
    """Raised on schema parse or validation failure."""


# ---------------------------------------------------------------------------
# IR
# ---------------------------------------------------------------------------


class Expr:
    """Base class for permission userset-rewrite expressions."""

    __slots__ = ()


@dataclass(frozen=True)
class RelationRef(Expr):
    """A reference to a relation or permission on the same definition
    (SpiceDB _this / computed_userset)."""

    name: str

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Arrow(Expr):
    """Tupleset-to-userset: ``tupleset->target`` — walk the ``tupleset``
    relation, then evaluate ``target`` on each subject found."""

    tupleset: str
    target: str

    def __str__(self) -> str:
        return f"{self.tupleset}->{self.target}"


@dataclass(frozen=True)
class Union(Expr):
    operands: tuple[Expr, ...]

    def __str__(self) -> str:
        return "(" + " + ".join(map(str, self.operands)) + ")"


@dataclass(frozen=True)
class Intersect(Expr):
    operands: tuple[Expr, ...]

    def __str__(self) -> str:
        return "(" + " & ".join(map(str, self.operands)) + ")"


@dataclass(frozen=True)
class Exclude(Expr):
    """``base - subtract``"""

    base: Expr
    subtract: Expr

    def __str__(self) -> str:
        return f"({self.base} - {self.subtract})"


@dataclass(frozen=True)
class Nil(Expr):
    """``nil`` — the empty userset (bootstrap.yaml's ``no_one_at_all``)."""

    def __str__(self) -> str:
        return "nil"


@dataclass(frozen=True)
class AllowedSubject:
    """One member of a relation's subject-type union.

    ``relation viewer: user | group#member | user:* | activity with expiration``
    """

    type: str
    relation: Optional[str] = None  # userset subjects: group#member
    wildcard: bool = False  # user:*
    expiration: bool = False  # `with expiration` trait
    caveat: Optional[str] = None  # `with <caveat>` trait (validated as
    #                               declared; enforced by caveats/)

    def __str__(self) -> str:
        s = self.type
        if self.wildcard:
            s += ":*"
        if self.relation:
            s += f"#{self.relation}"
        if self.expiration:
            s += " with expiration"
        return s


@dataclass
class Relation:
    name: str
    allowed: list[AllowedSubject]


@dataclass
class Permission:
    name: str
    expr: Expr


@dataclass
class Definition:
    name: str
    relations: dict[str, Relation] = field(default_factory=dict)
    permissions: dict[str, Permission] = field(default_factory=dict)

    def relation_or_permission(self, name: str):
        return self.relations.get(name) or self.permissions.get(name)


@dataclass
class Schema:
    definitions: dict[str, Definition] = field(default_factory=dict)
    use_expiration: bool = False
    # DECLARED caveat names (parse_caveat): distinguishes tuple traits
    # from typos — an UNDECLARED bracket trait (e.g. a misspelled
    # expiration) fails loudly instead of silently dropping the grant
    caveats: set = field(default_factory=set)
    # name -> caveats.ast.CaveatDef: the typed parameter list + body AST
    # the caveat compiler lowers into the vectorized expression VM
    # (caveats/compile.py); conditional grants are ENFORCED on-device
    caveat_defs: dict = field(default_factory=dict)

    def definition(self, name: str) -> Definition:
        try:
            return self.definitions[name]
        except KeyError:
            raise SchemaError(f"unknown definition {name!r}") from None


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<num>\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:/[A-Za-z_][A-Za-z0-9_]*)*)
  | (?P<op>->|[=!<>]=|&&|\|\||[{}():|+&#*,=<>!./\[\]-])
    """,
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {"definition", "relation", "permission", "use", "nil", "with", "caveat"}


@dataclass
class _Tok:
    kind: str  # 'ident' | 'op' | 'eof'
    value: str
    pos: int
    line: int


def _tokenize(text: str) -> Iterator[_Tok]:
    pos = 0
    line = 1
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise SchemaError(f"schema: unexpected character {text[pos]!r} at line {line}")
        pos = m.end()
        if m.lastgroup == "ws":
            line += m.group().count("\n")
            continue
        yield _Tok(m.lastgroup, m.group(), m.start(), line)
    yield _Tok("eof", "", pos, line)


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = list(_tokenize(text))
        self.i = 0
        # enclosing-scope breadcrumbs for error messages: operators
        # editing a 500-line schema need "in definition 'pod', relation
        # 'viewer'", not a bare line number (advisor DX, ISSUE 19)
        self._ctx_def: Optional[str] = None
        self._ctx_member: Optional[str] = None

    @property
    def cur(self) -> _Tok:
        return self.toks[self.i]

    def _where(self) -> str:
        if self._ctx_def is None:
            return ""
        if self._ctx_member is None:
            return f" (in definition {self._ctx_def!r})"
        return (f" (in definition {self._ctx_def!r}, "
                f"{self._ctx_member})")

    def fail(self, line: int, msg: str) -> "SchemaError":
        return SchemaError(f"schema line {line}{self._where()}: {msg}")

    def advance(self) -> _Tok:
        t = self.cur
        if t.kind != "eof":
            self.i += 1
        return t

    def expect(self, value: str) -> _Tok:
        t = self.cur
        if t.value != value:
            raise self.fail(
                t.line, f"expected {value!r}, got {t.value or 'EOF'!r}")
        return self.advance()

    def expect_ident(self) -> str:
        t = self.cur
        if t.kind != "ident":
            raise self.fail(
                t.line, f"expected identifier, got {t.value!r}")
        if t.value in KEYWORDS:
            # Keywords are reserved: a relation named `nil` would otherwise
            # silently parse as the empty userset in permission expressions.
            raise self.fail(t.line, f"{t.value!r} is a reserved keyword")
        self.advance()
        return t.value

    # -- grammar -----------------------------------------------------------

    def parse(self) -> Schema:
        schema = Schema()
        while self.cur.kind != "eof":
            if self.cur.value == "use":
                self.advance()
                feature = self.expect_ident()
                if feature == "expiration":
                    schema.use_expiration = True
                # unknown `use` features are tolerated (forward compat)
            elif self.cur.value == "definition":
                d = self.parse_definition()
                if d.name in schema.definitions:
                    raise SchemaError(f"duplicate definition {d.name!r}")
                schema.definitions[d.name] = d
            elif self.cur.value == "caveat":
                defn = self.parse_caveat()
                if defn.name in schema.caveat_defs:
                    raise SchemaError(f"duplicate caveat {defn.name!r}")
                schema.caveats.add(defn.name)
                schema.caveat_defs[defn.name] = defn
            else:
                raise SchemaError(
                    f"schema line {self.cur.line}: expected 'definition', got {self.cur.value!r}"
                )
        _validate(schema)
        return schema

    def parse_caveat(self):
        """``caveat name(param type, ...) { expr }`` -> a typed
        :class:`~...caveats.ast.CaveatDef`. The parameter list follows
        SpiceDB (``day string``; a ``day: string`` colon is tolerated);
        the body is handed to the caveat expression parser
        (caveats/ast.py) and type-checked by compiling it against a
        scratch interner, so a malformed caveat fails the SCHEMA parse
        instead of the first query that touches it."""
        from ..caveats.ast import (
            CaveatDef,
            CaveatError,
            CaveatParam,
            CaveatType,
            SCALAR_TYPES,
            parse_caveat_body,
        )
        from ..caveats.compile import typecheck

        self.expect("caveat")
        name = self.expect_ident()
        self.expect("(")
        params: list = []

        def parse_type() -> CaveatType:
            t = self.cur
            if t.kind != "ident":
                raise SchemaError(
                    f"schema line {t.line}: expected a caveat parameter "
                    f"type, got {t.value!r}")
            self.advance()
            if t.value == "list":
                self.expect("<")
                elem = self.cur
                if elem.kind != "ident" or elem.value not in SCALAR_TYPES:
                    raise SchemaError(
                        f"schema line {elem.line}: unsupported list "
                        f"element type {elem.value!r}")
                self.advance()
                self.expect(">")
                return CaveatType("list", elem.value)
            if t.value not in SCALAR_TYPES:
                raise SchemaError(
                    f"schema line {t.line}: unsupported caveat "
                    f"parameter type {t.value!r}")
            return CaveatType(t.value)

        if self.cur.value != ")":
            while True:
                pname = self.expect_ident()
                if self.cur.value == ":":  # tolerated `name: type` form
                    self.advance()
                params.append(CaveatParam(pname, parse_type()))
                if self.cur.value != ",":
                    break
                self.advance()
        self.expect(")")
        open_tok = self.expect("{")
        depth = 1
        while True:
            t = self.advance()
            if t.kind == "eof":
                raise SchemaError("unterminated caveat block")
            if t.value == "{":
                depth += 1
            elif t.value == "}":
                depth -= 1
                if depth == 0:
                    close_tok = t
                    break
        body = self.text[open_tok.pos + 1:close_tok.pos]
        try:
            defn = CaveatDef(name, tuple(params), parse_caveat_body(body))
            typecheck(defn)
        except CaveatError as e:
            raise SchemaError(f"caveat {name!r}: {e}") from None
        return defn

    def parse_definition(self) -> Definition:
        self.expect("definition")
        name = self.expect_ident()
        d = Definition(name)
        self._ctx_def = name
        self.expect("{")
        while self.cur.value != "}":
            if self.cur.value == "relation":
                r = self.parse_relation()
                if r.name in d.relations or r.name in d.permissions:
                    raise SchemaError(
                        f"definition {name!r}: duplicate "
                        f"relation/permission {r.name!r}")
                d.relations[r.name] = r
            elif self.cur.value == "permission":
                p = self.parse_permission()
                if p.name in d.relations or p.name in d.permissions:
                    raise SchemaError(
                        f"definition {name!r}: duplicate "
                        f"relation/permission {p.name!r}")
                d.permissions[p.name] = p
            else:
                raise self.fail(
                    self.cur.line,
                    f"expected relation/permission, got {self.cur.value!r}")
        self.expect("}")
        self._ctx_def = None
        return d

    def parse_relation(self) -> Relation:
        self.expect("relation")
        name = self.expect_ident()
        self._ctx_member = f"relation {name!r}"
        self.expect(":")
        allowed = [self.parse_allowed_subject()]
        while self.cur.value == "|":
            self.advance()
            allowed.append(self.parse_allowed_subject())
        self._ctx_member = None
        return Relation(name, allowed)

    def parse_allowed_subject(self) -> AllowedSubject:
        typ = self.expect_ident()
        wildcard = False
        relation = None
        expiration = False
        if self.cur.value == ":":
            self.advance()
            self.expect("*")
            wildcard = True
        if self.cur.value == "#":
            self.advance()
            relation = self.expect_ident()
        caveat = None
        while self.cur.value == "with":
            self.advance()
            while True:
                trait = self.expect_ident()
                if trait == "expiration":
                    expiration = True
                else:
                    # a caveated subject type (`user with ip_allowlist`):
                    # tuples carrying the caveat are conditional grants,
                    # enforced on-device by the caveat VM (caveats/).
                    # _validate still requires the name to be DECLARED,
                    # so a misspelled `expiration` cannot slip through
                    # as a phantom caveat.
                    caveat = trait
                # SpiceDB chains traits with `and`:
                # `user with some_caveat and expiration`
                if self.cur.value != "and":
                    break
                self.advance()
        return AllowedSubject(typ, relation, wildcard, expiration, caveat)

    def parse_permission(self) -> Permission:
        self.expect("permission")
        name = self.expect_ident()
        self._ctx_member = f"permission {name!r}"
        self.expect("=")
        expr = self.parse_expr()
        self._ctx_member = None
        return Permission(name, expr)

    def parse_expr(self) -> Expr:
        left = self.parse_term()
        first_op = None
        while self.cur.value in ("+", "&", "-"):
            op = self.advance().value
            # SpiceDB rejects unparenthesized mixing of different operators;
            # silently picking an associativity would change grants
            if first_op is None:
                first_op = op
            elif op != first_op:
                raise self.fail(
                    self.cur.line,
                    f"mixing {first_op!r} and {op!r} requires parentheses")
            right = self.parse_term()
            if op == "+":
                if isinstance(left, Union):
                    left = Union(left.operands + (right,))
                else:
                    left = Union((left, right))
            elif op == "&":
                if isinstance(left, Intersect):
                    left = Intersect(left.operands + (right,))
                else:
                    left = Intersect((left, right))
            else:
                left = Exclude(left, right)
        return left

    def parse_term(self) -> Expr:
        if self.cur.value == "(":
            self.advance()
            e = self.parse_expr()
            self.expect(")")
            return e
        if self.cur.value == "nil":
            self.advance()
            return Nil()
        name = self.expect_ident()
        if self.cur.value == "->":
            self.advance()
            target = self.expect_ident()
            return Arrow(name, target)
        return RelationRef(name)


def _walk(expr: Expr) -> Iterator[Expr]:
    yield expr
    if isinstance(expr, (Union, Intersect)):
        for op in expr.operands:
            yield from _walk(op)
    elif isinstance(expr, Exclude):
        yield from _walk(expr.base)
        yield from _walk(expr.subtract)


def _validate(schema: Schema) -> None:
    for d in schema.definitions.values():
        for r in d.relations.values():
            for a in r.allowed:
                if a.type not in schema.definitions:
                    raise SchemaError(
                        f"{d.name}#{r.name}: unknown subject type {a.type!r}"
                    )
                if a.caveat is not None and a.caveat not in schema.caveats:
                    # tolerate only DECLARED caveats: `with expirations`
                    # (a typo) must fail the parse loudly, not become a
                    # phantom caveat that silently drops grants
                    raise SchemaError(
                        f"{d.name}#{r.name}: unknown trait {a.caveat!r} "
                        "(not 'expiration' and no such caveat declared)"
                    )
                if a.relation is not None:
                    sub = schema.definitions[a.type]
                    if sub.relation_or_permission(a.relation) is None:
                        raise SchemaError(
                            f"{d.name}#{r.name}: unknown subject relation "
                            f"{a.type}#{a.relation}"
                        )
        for p in d.permissions.values():
            for node in _walk(p.expr):
                if isinstance(node, RelationRef):
                    if d.relation_or_permission(node.name) is None:
                        raise SchemaError(
                            f"{d.name}#{p.name}: unknown relation {node.name!r}"
                        )
                elif isinstance(node, Arrow):
                    rel = d.relations.get(node.tupleset)
                    if rel is None:
                        raise SchemaError(
                            f"{d.name}#{p.name}: arrow tupleset {node.tupleset!r} "
                            "must be a relation on the same definition"
                        )
                    # SpiceDB rejects arrows over wildcard-able tuplesets —
                    # a wildcard subject cannot be walked.
                    if any(a.wildcard for a in rel.allowed):
                        raise SchemaError(
                            f"{d.name}#{p.name}: arrow tupleset {node.tupleset!r} "
                            "allows wildcard subjects and cannot be walked"
                        )
                    # target must exist on at least one allowed subject type
                    ok = any(
                        schema.definitions[a.type].relation_or_permission(node.target)
                        for a in rel.allowed
                        if not a.relation  # arrows walk concrete subjects
                    )
                    if not ok:
                        raise SchemaError(
                            f"{d.name}#{p.name}: arrow target {node.target!r} not "
                            f"found on any subject type of {node.tupleset!r}"
                        )


def watch_relevance(schema: Schema, resource_type: str,
                    name: str) -> "tuple[frozenset, bool]":
    """(relevant resource types, reachable expiration) for the permission
    (or relation) ``resource_type#name``. Tuples are keyed by their
    resource type, so a write to a type outside the set provably cannot
    change the permission — watch streams use that to skip allowed-set
    recomputes on unrelated write traffic. The expiration flag is true only
    when some RELATION REACHABLE from the watched permission allows
    ``with expiration`` — a schema carrying expiration on an unrelated
    subtree must not make every idle watcher tick (advisor r3). Both are
    conservative at TYPE granularity; cycles (recursive groups) terminate
    via the seen set."""
    seen: set = set()
    types: set = set()
    expires = False

    def visit(t: str, r: str) -> None:
        nonlocal expires
        if (t, r) in seen:
            return
        seen.add((t, r))
        d = schema.definitions.get(t)
        if d is None:
            return
        types.add(t)
        if r in d.permissions:
            walk(t, d.permissions[r].expr, d)
        elif r in d.relations:
            for a in d.relations[r].allowed:
                if a.expiration:
                    expires = True
                if a.relation:
                    visit(a.type, a.relation)

    def walk(t: str, expr: Expr, d: Definition) -> None:
        if isinstance(expr, RelationRef):
            visit(t, expr.name)
        elif isinstance(expr, Arrow):
            visit(t, expr.tupleset)
            rel = d.relations.get(expr.tupleset)
            for a in (rel.allowed if rel else ()):
                visit(a.type, expr.target)
        elif isinstance(expr, (Union, Intersect)):
            for o in expr.operands:
                walk(t, o, d)
        elif isinstance(expr, Exclude):
            walk(t, expr.base, d)
            walk(t, expr.subtract, d)

    visit(resource_type, name)
    return frozenset(types), expires


def relevant_resource_types(schema: Schema, resource_type: str,
                            name: str) -> frozenset:
    """Resource types whose relationship writes can affect
    ``resource_type#name`` (see :func:`watch_relevance`)."""
    return watch_relevance(schema, resource_type, name)[0]


def parse_schema(text: str) -> Schema:
    """Parse schema DSL text into a validated :class:`Schema`."""
    return _Parser(text).parse()


# ---------------------------------------------------------------------------
# Schema diff classifier (live migration, ISSUE 19)
# ---------------------------------------------------------------------------

# transition classes, ordered by how much work the migrator must do
ADDITIVE = "additive"  # no tuple rewrites: swap graphs at a revision
REWRITING = "rewriting"  # affected tuples re-validated + journaled backfill
INCOMPATIBLE = "incompatible"  # refused before any state changes


class IncompatibleSchemaChange(SchemaError):
    """Typed refusal: the S -> S' transition cannot be performed online
    (or at all) without operator intervention. Raised BEFORE any engine
    state changes; ``reasons`` carries one line per blocking change."""

    def __init__(self, reasons: "tuple[str, ...]"):
        self.reasons = tuple(reasons)
        super().__init__(
            "incompatible schema change: " + "; ".join(self.reasons))


@dataclass(frozen=True)
class SchemaDiff:
    """The classified S -> S' transition.

    ``changed`` is the core set of ``(definition, member)`` pairs whose
    own declaration differs between the schemas (member = relation or
    permission name; a changed caveat body contributes every relation
    that allows it). ``affected`` is the reachability closure over S':
    every ``(definition, member)`` whose verdict CAN change — i.e. whose
    walk (the same conservative walk `watch_relevance` uses) touches a
    changed element. Everything outside ``affected`` must keep its
    cached decisions and never flap mid-migration; the chaos invariant
    machine-checks that. All members are frozensets, so the diff is
    order-independent by construction: permuting S' definitions yields
    an equal SchemaDiff (pinned by a property test)."""

    classification: str  # ADDITIVE | REWRITING | INCOMPATIBLE
    changed: frozenset  # core {(def, member)} that differ
    affected: frozenset  # closure {(def, member)} whose verdicts may move
    # relations whose TUPLES need re-validation/backfill: the subset of
    # `changed` where the allowed-subject set itself moved
    rewrite_relations: frozenset  # {(def, relation)}
    reasons: tuple = ()  # human-readable, one per contributing change

    def is_affected(self, definition: str, member: str) -> bool:
        return (definition, member) in self.affected


def _allowed_key(a: AllowedSubject) -> tuple:
    return (a.type, a.relation, a.wildcard, a.expiration, a.caveat)


def _member_reach(schema: Schema, dname: str, member: str) -> frozenset:
    """All (definition, relation-or-permission) pairs reachable from
    ``dname#member`` — the same conservative walk as watch_relevance,
    but at MEMBER granularity so the affected closure can spare
    unrelated relations on a shared definition."""
    seen: set = set()

    def visit(t: str, r: str) -> None:
        if (t, r) in seen:
            return
        seen.add((t, r))
        d = schema.definitions.get(t)
        if d is None:
            return
        if r in d.permissions:
            walk(t, d.permissions[r].expr, d)
        elif r in d.relations:
            for a in d.relations[r].allowed:
                if a.relation:
                    visit(a.type, a.relation)

    def walk(t: str, expr: Expr, d: Definition) -> None:
        if isinstance(expr, RelationRef):
            visit(t, expr.name)
        elif isinstance(expr, Arrow):
            visit(t, expr.tupleset)
            rel = d.relations.get(expr.tupleset)
            for a in (rel.allowed if rel else ()):
                visit(a.type, expr.target)
        elif isinstance(expr, (Union, Intersect)):
            for o in expr.operands:
                walk(t, o, d)
        elif isinstance(expr, Exclude):
            walk(t, expr.base, d)
            walk(t, expr.subtract, d)

    visit(dname, member)
    return frozenset(seen)


def diff_schemas(old: Schema, new: Schema) -> SchemaDiff:
    """Classify the ``old`` -> ``new`` transition for live migration.

    - **additive**: new definitions/relations/permissions/caveats, or a
      permission expression change — nothing stored needs rewriting, the
      new graph swaps in at a revision.
    - **rewriting**: an existing relation's allowed-subject set changed
      compatibly (entries gained, or traits attached — e.g. a caveat on
      a live relation) or a declared caveat's definition changed: every
      stored tuple on those relations is re-validated and backfilled
      through the journaled write path before the cut.
    - **incompatible**: removals or kind flips (definition dropped,
      relation/permission dropped, relation<->permission flip, an
      allowed-subject entry dropped, a referenced caveat dropped) —
      stored tuples could be stranded, so the transition is refused
      with :class:`IncompatibleSchemaChange` before any state changes.

    Comparison is purely name-keyed + frozenset-based, so definition
    order in either schema text never changes the result.
    """
    changed: set = set()
    rewrite_relations: set = set()
    reasons: list = []
    fatal: list = []

    # --- caveat declarations -------------------------------------------
    changed_caveats: set = set()
    for cname, cdef in old.caveat_defs.items():
        if cname not in new.caveat_defs:
            # dropping a caveat still allowed by some OLD relation means
            # live conditional tuples lose their evaluator
            used = [f"{d.name}#{r.name}"
                    for d in old.definitions.values()
                    for r in d.relations.values()
                    if any(a.caveat == cname for a in r.allowed)]
            if used:
                fatal.append(
                    f"caveat {cname!r} removed while still allowed by "
                    + ", ".join(sorted(used)))
            else:
                changed_caveats.add(cname)
                reasons.append(f"caveat {cname!r} removed (unused)")
        elif new.caveat_defs[cname] != cdef:
            changed_caveats.add(cname)
            reasons.append(f"caveat {cname!r} definition changed")
    for cname in new.caveat_defs:
        if cname not in old.caveat_defs:
            reasons.append(f"caveat {cname!r} added")

    # --- definitions and members ---------------------------------------
    for dname, od in old.definitions.items():
        nd = new.definitions.get(dname)
        if nd is None:
            fatal.append(f"definition {dname!r} removed")
            continue
        for rname, orel in od.relations.items():
            if rname in nd.permissions:
                fatal.append(
                    f"{dname}#{rname} changed kind relation->permission")
                continue
            nrel = nd.relations.get(rname)
            if nrel is None:
                fatal.append(f"relation {dname}#{rname} removed")
                continue
            old_allowed = frozenset(map(_allowed_key, orel.allowed))
            new_allowed = frozenset(map(_allowed_key, nrel.allowed))
            lost = old_allowed - new_allowed
            gained = new_allowed - old_allowed
            # trait attach/detach shows up as lost+gained on the same
            # (type, relation, wildcard) base; losing the BASE subject
            # entirely strands its tuples -> incompatible
            base = lambda k: k[:3]  # noqa: E731 - local key projection
            lost_bases = {base(k) for k in lost}
            kept_bases = {base(k) for k in new_allowed}
            stranded = lost_bases - kept_bases
            if stranded:
                fatal.append(
                    f"relation {dname}#{rname} dropped subject type(s) "
                    + ", ".join(sorted(str(b[0]) for b in stranded)))
                continue
            if lost or gained:
                changed.add((dname, rname))
                rewrite_relations.add((dname, rname))
                reasons.append(
                    f"relation {dname}#{rname} allowed-subject set "
                    "changed (tuples re-validated)")
            elif any(a.caveat in changed_caveats for a in orel.allowed):
                changed.add((dname, rname))
                rewrite_relations.add((dname, rname))
                reasons.append(
                    f"relation {dname}#{rname} rides a changed caveat")
        for pname, operm in od.permissions.items():
            if pname in nd.relations:
                fatal.append(
                    f"{dname}#{pname} changed kind permission->relation")
                continue
            nperm = nd.permissions.get(pname)
            if nperm is None:
                fatal.append(f"permission {dname}#{pname} removed")
                continue
            if nperm.expr != operm.expr:
                changed.add((dname, pname))
                reasons.append(
                    f"permission {dname}#{pname} expression changed")
    for dname, nd in new.definitions.items():
        od = old.definitions.get(dname)
        if od is None:
            reasons.append(f"definition {dname!r} added")
            for m in list(nd.relations) + list(nd.permissions):
                changed.add((dname, m))
            continue
        for rname in nd.relations:
            if rname not in od.relations and rname not in od.permissions:
                changed.add((dname, rname))
                reasons.append(f"relation {dname}#{rname} added")
        for pname in nd.permissions:
            if pname not in od.permissions and pname not in od.relations:
                changed.add((dname, pname))
                reasons.append(f"permission {dname}#{pname} added")

    if fatal:
        return SchemaDiff(INCOMPATIBLE, frozenset(changed),
                          frozenset(changed), frozenset(),
                          tuple(sorted(fatal)))

    # --- affected closure over S' --------------------------------------
    changed_f = frozenset(changed)
    affected: set = set(changed_f)
    if changed_f:
        for dname, nd in new.definitions.items():
            for m in list(nd.relations) + list(nd.permissions):
                if (dname, m) in affected:
                    continue
                if _member_reach(new, dname, m) & changed_f:
                    affected.add((dname, m))

    cls = REWRITING if rewrite_relations else ADDITIVE
    return SchemaDiff(cls, changed_f, frozenset(affected),
                      frozenset(rewrite_relations), tuple(sorted(reasons)))


def ir_digest(schema: Schema) -> str:
    """Order-independent structural digest of a schema's IR — the
    migration layer's identity test ("did this boot's bootstrap already
    catch up to S'?"). Two schema texts that parse to the same
    definitions/caveats digest identically regardless of declaration
    order or formatting."""
    import hashlib

    parts = []
    for dname in sorted(schema.definitions):
        d = schema.definitions[dname]
        for rname in sorted(d.relations):
            allowed = sorted(map(_allowed_key, d.relations[rname].allowed),
                             key=repr)
            parts.append(f"R {dname}#{rname}:{allowed!r}")
        for pname in sorted(d.permissions):
            parts.append(f"P {dname}#{pname}={d.permissions[pname].expr}")
    for cname in sorted(schema.caveat_defs):
        parts.append(f"C {cname}:{schema.caveat_defs[cname]!r}")
    parts.append(f"use_expiration={schema.use_expiration}")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()[:16]


def require_compatible(old: Schema, new: Schema) -> SchemaDiff:
    """diff_schemas, but raise :class:`IncompatibleSchemaChange` (with
    every blocking reason) instead of returning an incompatible diff —
    the migrator's front door."""
    diff = diff_schemas(old, new)
    if diff.classification == INCOMPATIBLE:
        raise IncompatibleSchemaChange(diff.reasons)
    return diff
