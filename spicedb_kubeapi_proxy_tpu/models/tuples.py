"""Relationship tuples and their string form.

A relationship is ``resource_type:resource_id#relation@subject_type:subject_id``
optionally followed by ``#subject_relation`` (userset subject) and/or an
``[expiration:RFC3339]`` trait. Mirrors the reference's template grammar
(/root/reference/pkg/rules/rules.go:1050-1073) and SpiceDB's tuple string
format used in bootstrap ``relationships`` blocks
(/root/reference/pkg/spicedb/bootstrap.yaml:39-40).
"""

from __future__ import annotations

import json
import logging
import re
from dataclasses import dataclass, replace
from datetime import datetime, timezone
from typing import Optional

log = logging.getLogger("sdbkp.tuples")


class TupleError(ValueError):
    pass


def canonical_context(ctx) -> Optional[str]:
    """Canonical JSON for a caveat context: sorted keys, no whitespace —
    ONE string form per logical context, so (caveat, context) pairs
    intern/deduplicate by string equality and ``parse ∘ format`` is the
    identity on formatted strings. ``None``/empty -> ``None``."""
    if ctx is None:
        return None
    if isinstance(ctx, str):
        t = ctx.strip()
        if not t:
            return None
        try:
            ctx = json.loads(t)
        except ValueError as e:
            raise TupleError(f"invalid caveat context {ctx!r}: {e}") \
                from None
    if not isinstance(ctx, dict):
        raise TupleError(
            f"caveat context must be a JSON object, got {ctx!r}")
    if not ctx:
        return None
    try:
        return json.dumps(ctx, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError) as e:
        raise TupleError(f"unserializable caveat context: {e}") from None


# Template splitting (lenient): segments may contain '/', '.', '-', '{{ }}'
# templates, '$' wildcards etc.; only ':', '#', '@' are structural. Same
# shape as the reference's relRegex (rules.go:1050-1052).
_TPL_RE = re.compile(
    r"^(?P<resource_type>.*?):(?P<resource_id>.*?)#(?P<relation>.*?)"
    r"@(?P<subject_type>.*?):(?P<subject_id>.*?)(?:#(?P<subject_relation>.*?))?$"
)

# Concrete relationship strings (strict): types/relations are identifiers,
# ids allow the kube-ish charset (slashes for namespacedName, dots, dashes)
# plus '*' for wildcard subjects; an optional [expiration:...] trait must be
# a well-formed suffix — trailing garbage is rejected, not absorbed.
_IDENT = r"[A-Za-z_][A-Za-z0-9_/]*"
# ids additionally allow '@' (email-shaped subjects like user:alice@example.com)
# — unambiguous because the structural '@' separator is always preceded by
# '#relation', and relations cannot contain '@'.
_ID = r"[A-Za-z0-9_.=+/@-]+|\*"
_REL_CORE = (
    rf"(?P<resource_type>{_IDENT}):(?P<resource_id>{_ID})#(?P<relation>{_IDENT})"
    rf"@(?P<subject_type>{_IDENT}):(?P<subject_id>{_ID})"
    rf"(?:#(?P<subject_relation>{_IDENT}|\.\.\.))?"
)
_REL_RE = re.compile(
    "^" + _REL_CORE +
    # optional caveat trait (SpiceDB `[caveat_name]` /
    # `[caveat_name:{...context...}]`) BEFORE the expiration trait; the
    # lookahead keeps `[expiration:...]` out of the caveat group. Parsed
    # tolerantly — enforcement is warn-and-skip at load time
    rf"(?:\[(?!expiration[:\]])(?P<caveat>[A-Za-z_][A-Za-z0-9_/]*)"
    rf"(?::(?P<caveat_ctx>[^\]]*))?\])?"
    rf"(?:\[expiration:(?P<expiration>[^\]]+)\])?$"
)
# a caveat CONTEXT may carry JSON with nested ']' (e.g.
# `[ip_allowlist:{"ips":["10.0.0.0/8"]}]`), which the strict bracket
# grammar above cannot span: this fallback's non-greedy DOTALL context
# backtracks to the real closing bracket; canonical_context then
# validates the JSON, so malformed contexts fail loudly at parse time
_REL_CAVEAT_LENIENT_RE = re.compile(
    "^" + _REL_CORE +
    rf"\[(?!expiration[:\]])(?P<caveat>[A-Za-z_][A-Za-z0-9_/]*)"
    rf":(?P<caveat_ctx>.*?)\]"
    rf"(?:\[expiration:(?P<expiration>[^\]]+)\])?$",
    re.DOTALL,
)

ELLIPSIS = "..."


@dataclass(frozen=True)
class Relationship:
    resource_type: str
    resource_id: str
    relation: str
    subject_type: str
    subject_id: str
    subject_relation: Optional[str] = None  # userset subject, e.g. group#member
    expiration: Optional[float] = None  # unix seconds; None = never expires
    # caveat NAME when the grant is conditional (`[caveat_name]` /
    # `[caveat_name:{...}]`): the tuple participates in checks only when
    # the caveat's expression holds under tuple ∪ request context,
    # evaluated on-device by the caveat VM (caveats/)
    caveat: Optional[str] = None
    # the tuple's stored context as CANONICAL JSON (canonical_context:
    # sorted keys, compact separators) — a string, not a dict, so the
    # frozen dataclass stays hashable and parse↔format is lossless
    caveat_context: Optional[str] = None

    def key(self) -> tuple:
        """Identity key — expiration is an attribute, not identity (TOUCH
        overwrites the expiration of an existing tuple)."""
        return (
            self.resource_type,
            self.resource_id,
            self.relation,
            self.subject_type,
            self.subject_id,
            self.subject_relation or "",
        )

    def without_expiration(self) -> "Relationship":
        return replace(self, expiration=None)

    def context_dict(self) -> Optional[dict]:
        """The stored caveat context as a dict (None when uncaveated or
        context-free)."""
        if not self.caveat_context:
            return None
        return json.loads(self.caveat_context)

    def __str__(self) -> str:
        s = (
            f"{self.resource_type}:{self.resource_id}#{self.relation}"
            f"@{self.subject_type}:{self.subject_id}"
        )
        if self.subject_relation:
            s += f"#{self.subject_relation}"
        if self.caveat:
            # context serializes back losslessly: canonical JSON inside
            # the bracket, exactly what parse_relationship re-reads
            s += (f"[{self.caveat}:{self.caveat_context}]"
                  if self.caveat_context else f"[{self.caveat}]")
        if self.expiration is not None:
            ts = datetime.fromtimestamp(self.expiration, tz=timezone.utc)
            s += f"[expiration:{ts.strftime('%Y-%m-%dT%H:%M:%SZ')}]"
        return s


def parse_expiration(text: str) -> float:
    """RFC3339 → unix seconds."""
    t = text.strip()
    if t.endswith("Z"):
        t = t[:-1] + "+00:00"
    try:
        dt = datetime.fromisoformat(t)
    except ValueError as e:
        raise TupleError(f"invalid expiration {text!r}: {e}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def parse_relationship(text: str) -> Relationship:
    """Parse a concrete relationship string (no templates)."""
    t = text.strip()
    m = _REL_RE.match(t) or _REL_CAVEAT_LENIENT_RE.match(t)
    if not m:
        raise TupleError(f"invalid relationship: {text!r}")
    g = m.groupdict()
    sub_rel = g["subject_relation"] or None
    if sub_rel == ELLIPSIS:
        sub_rel = None
    exp = parse_expiration(g["expiration"]) if g["expiration"] else None
    caveat = g.get("caveat") or None
    # context canonicalizes at parse time (sorted keys, compact), so
    # parse -> format round-trips losslessly and identical logical
    # contexts intern to one store instance
    try:
        ctx = canonical_context(g.get("caveat_ctx")) if caveat else None
    except TupleError as e:
        # a bracket trait with a non-JSON payload is either a malformed
        # context or — more likely — an unknown trait misspelling a
        # structured one (`[expiry:2030-...]` for `[expiration:...]`)
        raise TupleError(
            f"unknown trait or malformed caveat context "
            f"[{caveat}:...] in {text.strip()!r}: {e}") from None
    return Relationship(
        g["resource_type"],
        g["resource_id"],
        g["relation"],
        g["subject_type"],
        g["subject_id"],
        sub_rel,
        exp,
        caveat,
        ctx,
    )


# Permissive charsets for literal template fields: the goal is rejecting
# STRUCTURAL leaks (a stray '#' splitting a subject relation, '@' inside a
# field), not constraining identifiers — kube subjects legitimately carry
# ':' (system:serviceaccount:ns:name) and label-derived relations '/'
# (app.kubernetes.io/name).
_TPL_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_./-]*$")
_TPL_ID_RE = re.compile(r"^(?:[A-Za-z0-9_.=+/:@-]+|\*)$")


def parse_rel_fields(text: str) -> dict:
    """Split a (possibly templated) relationship string into its six fields
    (reference ParseRelSring, rules.go:1056-1073). Fields containing a
    ``{{ }}`` expression are left for the rules engine to compile; purely
    literal fields are validated against the concrete charset so malformed
    strings (`...@user:alice#a#b`) fail at parse time, not at request
    time."""
    m = _TPL_RE.match(text.strip())
    if not m:
        raise TupleError(f"invalid relationship template: {text!r}")
    g = m.groupdict()
    out = {
        "resource_type": g["resource_type"],
        "resource_id": g["resource_id"],
        "relation": g["relation"],
        "subject_type": g["subject_type"],
        "subject_id": g["subject_id"],
        "subject_relation": g["subject_relation"] or None,
    }
    for k, v in out.items():
        if not v or "{{" in v:
            continue
        rx = _TPL_ID_RE if k in ("resource_id", "subject_id") \
            else _TPL_IDENT_RE
        if not rx.match(v) and v != "$":  # `$` = prefilter/filter wildcard
            raise TupleError(
                f"invalid relationship template field {k}={v!r} in {text!r}")
    return out
