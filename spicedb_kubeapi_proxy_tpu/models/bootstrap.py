"""Bootstrap file handling: ``{schema: <DSL>, relationships: <tuple lines>}``.

Same YAML shape the reference feeds its embedded SpiceDB
(/root/reference/pkg/spicedb/spicedb.go:18-29, bootstrap.yaml). Multiple
documents are allowed; schemas are concatenated and relationships appended.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import yaml

from .schema import Schema, parse_schema
from .tuples import Relationship, parse_relationship

# The proxy's own bookkeeping types (locks, idempotency keys), mirroring the
# reference's embedded bootstrap (/root/reference/pkg/spicedb/bootstrap.yaml:
# 29-38). parse_bootstrap appends any of these definitions a caller-provided
# schema is missing, so the dual-write engine's lock/idempotency tuples always
# validate.
WORKFLOW_DEFS = {
    "lock": "definition lock {\n  relation workflow: workflow\n}\n",
    "workflow": (
        "definition workflow {\n"
        "  relation idempotency_key: activity with expiration\n"
        "}\n"
    ),
    "activity": "definition activity {}\n",
}
WORKFLOW_SCHEMA = "\n".join(WORKFLOW_DEFS.values())

DEFAULT_BOOTSTRAP = """
schema: |-
  use expiration

  definition cluster {}
  definition user {}
  definition namespace {
    relation cluster: cluster
    relation creator: user
    relation viewer: user

    permission admin = creator
    permission edit = creator
    permission view = viewer + creator
    permission no_one_at_all = nil
  }
  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator
  }
  definition lock {
    relation workflow: workflow
  }
  definition workflow {
    relation idempotency_key: activity with expiration
  }
  definition activity {}
relationships: ""
"""


@dataclass
class Bootstrap:
    schema: Schema
    schema_text: str
    relationships: list[Relationship] = field(default_factory=list)


def parse_bootstrap(text: str) -> Bootstrap:
    schema_parts: list[str] = []
    rels: list[Relationship] = []
    for doc in yaml.safe_load_all(text):
        if doc is None:
            continue
        if not isinstance(doc, dict):
            raise ValueError("bootstrap document must be a mapping")
        if doc.get("schema"):
            schema_parts.append(str(doc["schema"]))
        rel_text = doc.get("relationships") or ""
        for line in str(rel_text).splitlines():
            line = line.strip()
            if not line or line.startswith("//") or line.startswith("#"):
                continue
            rels.append(parse_relationship(line))
    if not schema_parts:
        raise ValueError("bootstrap contains no schema")
    schema_text = "\n".join(schema_parts)
    missing = [
        name
        for name in ("lock", "workflow", "activity")
        if not re.search(rf"definition\s+{name}\b", schema_text)
    ]
    if missing:
        schema_text = "\n".join([schema_text] + [WORKFLOW_DEFS[n] for n in missing])
    schema = parse_schema(schema_text)
    # Caveated tuples LOAD with their contexts — conditional grants are
    # enforced on-device by the caveat VM (caveats/), resolving at check
    # time against tuple ∪ request context and failing closed on missing
    # context. Only DECLARED caveats are accepted — an unknown bracket
    # trait is far more likely a typo (e.g. [expiry:...] for
    # [expiration:...]), and silently dropping the grant would be a
    # quiet access revocation.
    for rel in rels:
        if rel.caveat and rel.caveat not in schema.caveats:
            raise ValueError(
                f"relationship {rel} carries unknown trait "
                f"[{rel.caveat}...]: no such caveat is declared in "
                "the schema — refusing to guess (a misspelled "
                "expiration would silently drop the grant)")
    return Bootstrap(schema, schema_text, rels)
