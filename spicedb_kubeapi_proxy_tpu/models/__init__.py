"""Schema models: the Zanzibar-style schema DSL parser and typed IR.

Covers the schema language surface the reference uses (see
/root/reference/pkg/spicedb/bootstrap.yaml:1-38 and e2e bootstrap schemas):
``use expiration``, ``definition``, ``relation`` with union subject types
(including userset subjects ``type#relation``, wildcard ``type:*`` and
``with expiration``), and ``permission`` expressions with union ``+``,
intersection ``&``, exclusion ``-``, arrows ``rel->perm`` and ``nil``.
"""

from .schema import (  # noqa: F401
    AllowedSubject,
    Arrow,
    Definition,
    Exclude,
    Expr,
    Intersect,
    Nil,
    Permission,
    Relation,
    RelationRef,
    Schema,
    SchemaError,
    Union,
    parse_schema,
)
from .bootstrap import parse_bootstrap, Bootstrap  # noqa: F401
