"""Request tracing: W3C traceparent context + cheap in-process spans.

One trace follows one proxy request end-to-end. The proxy ingress adopts
an incoming ``traceparent`` header (or mints one), the authz middleware
opens named child spans for every stage it runs (authn, rule match,
admission wait, cache probe, engine dispatch, post-filter, upstream RTT),
and the remote-engine wire carries the context as a frame field so
engine-host spans (queue wait, device dispatch, replication ack wait)
stitch into the proxy's trace — in-process when proxy and engine host
share an interpreter (the test/bench shape), by shared trace_id across
processes otherwise.

Recording is TAIL-sampled: spans are buffered on the live trace and the
keep/drop decision happens when the root finishes — error, shed, and
slow-threshold traces are always kept, the rest kept with probability
``sample``. Kept traces land in a lock-sharded ring buffer served by
``/debug/traces``. ``sample == 0`` disables tracing entirely: every hook
degrades to a couple of attribute reads, so the hot path pays nothing
measurable (the bench acceptance pin).

Spans cross threads explicitly: ``contextvars`` carry the active span
through ``asyncio`` tasks and ``asyncio.to_thread``, and executor-pool
hops (which do NOT copy context) re-enter via ``capture()`` /
``activate()``.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

_FLAG_SAMPLED = 0x01

# (trace, parent_span_id) of the code currently executing, or None
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "sdbkp_trace", default=None)


def parse_traceparent(header) -> Optional[tuple[str, str, int]]:
    """``(trace_id, parent_span_id, flags)`` from a W3C ``traceparent``
    (version 00), or ``None`` for anything malformed — a bad header from
    an arbitrary client must start a fresh trace, never raise."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
        f = int(flags, 16)
    except ValueError:
        return None
    return trace_id, span_id, f


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def _flag_exception(trace: "Trace", e: BaseException) -> None:
    """Trace-level flag for an exception crossing a span boundary: load
    sheds are the admission design WORKING and must stay distinguishable
    from real failures — they flag "shed", everything else "error" (both
    always survive tail sampling). Lazy import: only the exception path
    pays it, and obs/ stays import-light."""
    from ..admission import AdmissionRejected

    if isinstance(e, AdmissionRejected):
        trace.flag("shed")
    else:
        trace.flag("error")


def _new_trace_id() -> str:
    return f"{random.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{random.getrandbits(64):016x}"


class Span:
    """One named, timed segment of a trace. ``set()`` attaches attributes
    (JSON-safe values only); ``finish()`` records it onto its trace —
    callable from any thread, exactly once (later calls are ignored)."""

    __slots__ = ("trace", "span_id", "parent_id", "name", "start_epoch",
                 "_t0", "duration", "attrs", "_done")

    def __init__(self, trace: "Trace", parent_id: Optional[str], name: str,
                 attrs: Optional[dict] = None):
        self.trace = trace
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.start_epoch = time.time()
        self._t0 = time.perf_counter()
        self.duration = 0.0
        self.attrs = dict(attrs) if attrs else {}
        self._done = False

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def traceparent(self) -> str:
        return format_traceparent(self.trace.trace_id, self.span_id)

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self.duration = time.perf_counter() - self._t0
        self.trace.record(self)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start_epoch,
            "duration_us": int(self.duration * 1e6),
            "attrs": self.attrs,
        }


class _NullSpan:
    """The disabled-path stand-in: every hook stays unconditional at the
    call site while costing nothing."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def set(self, key, value) -> None:
        pass

    def traceparent(self):
        return None

    def finish(self) -> None:
        pass


NULL_SPAN = _NullSpan()


class Trace:
    """A live trace: the span accumulator plus trace-level flags. Spans
    append under a lock (proxy event loop, to_thread workers, and engine
    host executor threads all record concurrently)."""

    __slots__ = ("trace_id", "external", "flags", "spans", "start_epoch",
                 "_t0", "_lock")

    def __init__(self, trace_id: Optional[str] = None,
                 external: bool = False):
        self.trace_id = trace_id or _new_trace_id()
        # external: the root lives in ANOTHER process (an engine host
        # serving a remote proxy's op) — this trace holds a satellite
        # fragment, finished per-op instead of per-request
        self.external = external
        self.flags: dict = {}
        self.spans: list[Span] = []
        self.start_epoch = time.time()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def flag(self, key: str, value=True) -> None:
        self.flags[key] = value

    def stage_micros(self) -> dict:
        """Total finished-span duration per span name, in integer
        microseconds — the audit line's per-stage attribution."""
        out: dict[str, int] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0) + int(s.duration * 1e6)
        return out

    def to_dict(self) -> dict:
        with self._lock:
            spans = [s.to_dict() for s in self.spans]
        return {
            "trace_id": self.trace_id,
            "start": self.start_epoch,
            "duration_us": int((time.perf_counter() - self._t0) * 1e6),
            "flags": dict(self.flags),
            "external": self.external,
            "spans": spans,
        }


class Tracer:
    """The process-global span recorder (module-level ``tracer``).
    ``configure()`` is how flags reach it; every hook below is safe to
    call with tracing disabled or no active trace."""

    RING_SHARDS = 8

    def __init__(self, sample: float = 0.1, slow_ms: float = 250.0,
                 ring: int = 256):
        self._rand = random.random
        self._live_lock = threading.Lock()
        self._live: dict[str, Trace] = {}
        self.configure(sample=sample, slow_ms=slow_ms, ring=ring)

    def configure(self, sample: Optional[float] = None,
                  slow_ms: Optional[float] = None,
                  ring: Optional[int] = None, _rand=None) -> None:
        if sample is not None:
            self.sample = max(0.0, min(1.0, float(sample)))
        if slow_ms is not None:
            self.slow_s = max(0.0, float(slow_ms)) / 1e3
        if ring is not None:
            per = max(1, int(ring) // self.RING_SHARDS)
            self._shards = [(threading.Lock(), deque(maxlen=per))
                            for _ in range(self.RING_SHARDS)]
        if _rand is not None:
            self._rand = _rand

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    # -- context ------------------------------------------------------------

    def capture(self):
        """The active (trace, parent_span_id), for crossing an executor
        hop that does not copy contextvars; re-enter with
        :meth:`activate`."""
        return _CURRENT.get()

    @contextmanager
    def activate(self, captured):
        """Make a captured context the active one in THIS thread (worker
        pools; ``asyncio.to_thread`` copies context by itself)."""
        if captured is None:
            yield
            return
        token = _CURRENT.set(captured)
        try:
            yield
        finally:
            _CURRENT.reset(token)

    def current_trace(self) -> Optional[Trace]:
        cur = _CURRENT.get()
        return cur[0] if cur is not None else None

    def current_trace_id(self) -> Optional[str]:
        cur = _CURRENT.get()
        return cur[0].trace_id if cur is not None else None

    def current_traceparent(self) -> Optional[str]:
        cur = _CURRENT.get()
        if cur is None:
            return None
        return format_traceparent(cur[0].trace_id, cur[1])

    def flag(self, key: str, value=True) -> None:
        """Set a trace-level flag (error/shed/...) on the active trace;
        flagged traces survive tail sampling unconditionally."""
        cur = _CURRENT.get()
        if cur is not None:
            cur[0].flag(key, value)

    def flagged(self, key: str) -> bool:
        cur = _CURRENT.get()
        return bool(cur is not None and cur[0].flags.get(key))

    def stage_micros(self) -> dict:
        cur = _CURRENT.get()
        return cur[0].stage_micros() if cur is not None else {}

    # -- span lifecycle -----------------------------------------------------

    @contextmanager
    def start(self, name: str, traceparent: Optional[str] = None, **attrs):
        """Open a ROOT span (proxy ingress): adopts the trace_id from a
        valid incoming ``traceparent``, mints one otherwise. Exiting the
        context finishes the trace and runs the tail-sampling decision."""
        if not self.enabled:
            yield NULL_SPAN
            return
        parsed = parse_traceparent(traceparent)
        trace = Trace(parsed[0] if parsed else None)
        root = Span(trace, parsed[1] if parsed else None, name, attrs)
        with self._live_lock:
            if trace.trace_id in self._live:
                # a second in-flight request reusing the same incoming
                # traceparent (client retry racing its original): sharing
                # the live entry would cross-stitch engine-host spans and
                # stage timings between unrelated requests — mint a fresh
                # trace and keep the client's id as an attribute
                requested = trace.trace_id
                trace = Trace()
                root = Span(trace, None, name, attrs)
                root.set("requested_trace_id", requested)
            self._live[trace.trace_id] = trace
        token = _CURRENT.set((trace, root.span_id))
        try:
            yield root
        except BaseException as e:
            root.set("error", repr(e))
            _flag_exception(trace, e)
            raise
        finally:
            _CURRENT.reset(token)
            root.finish()
            with self._live_lock:
                if self._live.get(trace.trace_id) is trace:
                    del self._live[trace.trace_id]
            self._tail_decide(trace, root)

    @contextmanager
    def span(self, name: str, **attrs):
        """A child span of whatever is active; a no-op stand-in when
        nothing is (or tracing is off). Exceptions mark the span AND flag
        the trace as error before propagating."""
        cur = _CURRENT.get()
        if cur is None or not self.enabled:
            yield NULL_SPAN
            return
        trace, parent = cur
        sp = Span(trace, parent, name, attrs)
        token = _CURRENT.set((trace, sp.span_id))
        try:
            yield sp
        except BaseException as e:
            sp.set("error", repr(e))
            _flag_exception(trace, e)
            raise
        finally:
            _CURRENT.reset(token)
            sp.finish()

    def begin(self, name: str, **attrs) -> Optional[Span]:
        """Open a LEAF span without touching the context — for async
        dispatch paths whose completion callback runs elsewhere; the
        caller owns ``finish()``. Children never nest under it."""
        cur = _CURRENT.get()
        if cur is None or not self.enabled:
            return None
        trace, parent = cur
        return Span(trace, parent, name, attrs)

    @contextmanager
    def adopt(self, wire: Optional[str], name: str, **attrs):
        """Engine-host entry: attach to the trace named by a wire-carried
        ``traceparent``. When the trace is LIVE in this process (proxy and
        engine host sharing an interpreter), spans stitch straight into
        it; otherwise a satellite trace fragment is recorded under the
        same trace_id and tail-sampled on its own when the op ends."""
        parsed = parse_traceparent(wire) if wire else None
        if parsed is None or not self.enabled:
            yield NULL_SPAN
            return
        trace_id, parent_id, _flags = parsed
        with self._live_lock:
            live = self._live.get(trace_id)
        if live is not None:
            sp = Span(live, parent_id, name, attrs)
            token = _CURRENT.set((live, sp.span_id))
            try:
                yield sp
            except BaseException as e:
                sp.set("error", repr(e))
                _flag_exception(live, e)
                raise
            finally:
                _CURRENT.reset(token)
                sp.finish()
            return
        trace = Trace(trace_id, external=True)
        root = Span(trace, parent_id, name, attrs)
        token = _CURRENT.set((trace, root.span_id))
        try:
            yield root
        except BaseException as e:
            root.set("error", repr(e))
            _flag_exception(trace, e)
            raise
        finally:
            _CURRENT.reset(token)
            root.finish()
            self._tail_decide(trace, root)

    # -- tail sampling + ring -----------------------------------------------

    def _tail_decide(self, trace: Trace, root: Span) -> None:
        keep = (bool(trace.flags)
                or root.duration >= self.slow_s
                or self._rand() < self.sample)
        if not keep:
            return
        lock, ring = self._shards[hash(trace.trace_id) % self.RING_SHARDS]
        with lock:
            ring.append(trace.to_dict())

    def recent(self, limit: int = 64) -> list[dict]:
        """Most recent kept traces, newest first."""
        out: list[dict] = []
        for lock, ring in self._shards:
            with lock:
                out.extend(ring)
        out.sort(key=lambda t: t["start"], reverse=True)
        return out[:max(0, int(limit))]

    def reset(self) -> None:
        """Drop every kept trace (tests)."""
        for lock, ring in self._shards:
            with lock:
                ring.clear()


tracer = Tracer()
