"""Observability: request tracing, decision audit log, engine profiling.

The proxy's whole value is *explainable* authorization; this package makes
one request followable end-to-end — admission → cache → device fixpoint →
replication → upstream — and every decision auditable:

- :mod:`.trace` — cheap in-process spans under a W3C ``traceparent``
  context, recorded into a lock-sharded ring buffer with tail sampling
  (error/shed/slow traces always kept). Served at ``/debug/traces``.
- :mod:`.audit` — one JSON line per authorization decision
  (``--audit-log``), denies always, allows rate-capped.
- :mod:`.profile` — JAX compile-event hooks feeding the metrics registry.
"""

from .audit import AuditLog
from .trace import (
    Tracer,
    format_traceparent,
    parse_traceparent,
    tracer,
)

__all__ = [
    "AuditLog",
    "Tracer",
    "format_traceparent",
    "parse_traceparent",
    "tracer",
]
