"""Engine profiling hooks: JAX compile events into the metrics registry.

TrieJax-style kernel accounting (PAPERS.md) for the parts the engine
cannot time itself: XLA compilation happens inside jax, invisibly to the
dispatch path, yet a recompile is the single largest latency cliff the
engine has (tens of seconds at the 10M-relationship scale). jax's
monitoring module broadcasts event durations; the listener below mirrors
every compile-shaped event into ``jax_compile_seconds`` /
``jax_compile_events_total`` so a scrape (or bench.py's per-phase stage
breakdown) can attribute a p99 spike to compilation instead of guessing.

The other profiling hooks live where the numbers are produced:
CSR nnz / slot-space gauges at graph compile (engine/engine.py
``compiled()``), dispatch batch-size and frontier-occupancy histograms on
the query paths, queue-wait on the admission controller, replication ack
wait on the mirrored engine.
"""

from __future__ import annotations

import threading

from ..utils.metrics import metrics

_install_lock = threading.Lock()
_installed = False


def _on_event_duration(event: str, duration: float, **kw) -> None:
    # jax event names are path-ish ("/jax/core/compile/..."); anything
    # compile-shaped counts — backend_compile, pjit compile, tracing not
    if "compile" not in event:
        return
    metrics.counter("jax_compile_events_total").inc()
    metrics.histogram(
        "jax_compile_seconds",
        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                 60.0, 120.0)).observe(float(duration))


def install_jax_compile_hook() -> bool:
    """Register the compile-event listener once per process; True when a
    listener is (now or already) installed. Safe without jax or against a
    jax whose monitoring surface moved — profiling is best-effort, the
    engine must not fail to boot over it."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception:  # noqa: BLE001 - any jax/API-drift failure
            return False
        _installed = True
        return True
