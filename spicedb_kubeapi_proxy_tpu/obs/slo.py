"""Live SLO monitor: declared objectives + multi-window burn rates.

An :class:`Objective` declares what "good" means for one operation class:
a latency threshold (observations at or under it are good) and a target
good fraction (e.g. 0.999 = "99.9% of checks complete within 25ms").
Badness has two sources, both read from the EXISTING instrumentation —
no new hot-path hooks:

- latency: the objective's histogram family (``utils/metrics.py``
  windowed snapshots — the same machinery bench.py stage breakdowns
  use), counting observations above the threshold;
- availability: optional counter families (shed / error totals) whose
  window delta is added to the bad count AND the event total — a shed
  request never completed, so it can't hide in the latency histogram.

The monitor samples every registered source on a fixed tick into a
bounded ring, and computes, per objective and per window (default
1m/5m/1h), the **burn rate**: ``bad_fraction / (1 - target)``. Burn 1.0
means the error budget is being spent exactly at the rate that exhausts
it by the end of the SLO period; >1 burns faster (the standard
multi-window multi-burn alerting input). Exposed three ways:

- ``slo_burn_rate{objective=..,window=..}`` / ``slo_attainment{..}``
  gauges in the shared registry (scraped at ``/metrics``),
- :meth:`SLOMonitor.status` — the JSON document ``/debug/slo`` serves,
- the bench macro phase, which reports end-of-sweep attainment per class.

Latency goodness is bucket-resolution: "good" counts observations in
buckets whose upper bound is <= the threshold (+epsilon so a threshold
equal to a bound includes its own bucket). Declare thresholds on or near
bucket bounds — the default bucket ladder covers 0.5ms..10s.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.metrics import metrics

DEFAULT_WINDOWS = (60.0, 300.0, 3600.0)

# objective -> (histogram family, label filter, bad-counter families).
# These are the op classes the macrobench drives and the admission
# controller classifies; the latency sources are the histograms those
# code paths already observe.
_CLASS_SOURCES = {
    "check": ("engine_check_seconds", {},
              (("admission_shed_total", {"class": "check"}),
               ("admission_shed_total", {"class": "bulk-check"}))),
    "lookup": ("engine_lookup_seconds", {},
               (("admission_shed_total", {"class": "lookup-prefilter"}),)),
    "watch": ("watchhub_recompute_seconds", {},
              (("admission_shed_total", {"class": "watch-recompute"}),)),
    "request": ("proxy_request_seconds", {}, ()),
}


class SLOError(Exception):
    pass


@dataclass(frozen=True)
class Objective:
    """One declared objective: ``target`` fraction of ``name``-class
    events must be good (complete, at or under ``latency_ms``)."""

    name: str
    latency_ms: float
    target: float  # good fraction, e.g. 0.999
    histogram: str = ""  # metric family holding the class's latencies
    hist_labels: dict = field(default_factory=dict)
    # counter families whose window delta counts as bad AND as events
    # (sheds/errors never reach the latency histogram)
    bad_counters: tuple = ()


def parse_objectives(spec: str) -> list[Objective]:
    """``"check=25:99.9,lookup=100:99"`` -> objectives (latency ms :
    target percent). Classes must be known (the latency source is wired
    per class); raises :class:`SLOError` on anything malformed."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, rest = part.partition("=")
        name = name.strip()
        if not eq or name not in _CLASS_SOURCES:
            raise SLOError(
                f"unknown SLO class {name!r} (known: "
                f"{', '.join(sorted(_CLASS_SOURCES))}; format "
                "class=latency_ms:target_pct)")
        lat, colon, pct = rest.partition(":")
        try:
            latency_ms = float(lat)
            target = float(pct) / 100.0 if colon else 0.99
        except ValueError:
            raise SLOError(
                f"bad SLO spec {part!r} (format class=latency_ms"
                ":target_pct)") from None
        if latency_ms <= 0 or not 0.0 < target < 1.0:
            raise SLOError(
                f"bad SLO spec {part!r}: latency must be > 0 ms and "
                "target in (0, 100) percent")
        hist, labels, bad = _CLASS_SOURCES[name]
        out.append(Objective(name, latency_ms, target, hist,
                             dict(labels), bad))
    if not out:
        raise SLOError("empty SLO objective spec")
    return out


def default_objectives() -> list[Objective]:
    return parse_objectives("check=25:99.9,lookup=100:99,request=250:99")


class SLOMonitor:
    """Samples objective sources on a tick; answers burn-rate queries.

    The ring holds ``(ts, {objective: (events, bad)})`` cumulative
    samples; a window's burn rate is the delta between the newest sample
    and the oldest one inside the window. Ticking is either driven by
    the owned daemon thread (:meth:`start`) or called directly
    (:meth:`tick`) — tests and the bench sweep inject their own clock
    and cadence."""

    def __init__(self, objectives, windows=DEFAULT_WINDOWS,
                 tick_seconds: float = 5.0, clock=time.monotonic,
                 registry=metrics):
        if not objectives:
            raise SLOError("SLOMonitor needs at least one objective")
        self.objectives = list(objectives)
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows or self.windows[0] <= 0:
            raise SLOError("SLO windows must be > 0 seconds")
        self.tick_seconds = float(tick_seconds)
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        # samples are pruned by AGE (older than the longest window plus
        # slack), not by count: every /debug/slo read also appends a
        # sample, and a count-sized ring would silently shrink the span
        # the long windows actually measure under frequent reads. The
        # count cap is only a memory backstop.
        self._ring: list = []  # [(ts, {name: (events, bad)})]
        self._max_samples = 50_000
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        for o in self.objectives:
            registry.gauge("slo_objective_latency_ms",
                           objective=o.name).set(o.latency_ms)
            registry.gauge("slo_objective_target",
                           objective=o.name).set(o.target)
        self.tick()  # the baseline sample: burn rates read 0 until traffic

    # -- sampling -------------------------------------------------------------

    def _sample_objective(self, o: Objective) -> tuple[float, float]:
        """Cumulative (events, bad) for one objective right now."""
        events = bad = 0.0
        snap = self._registry.hist_snapshot(o.histogram, **o.hist_labels)
        if snap is not None:
            events += snap["n"]
            thresh = o.latency_ms / 1e3 * (1 + 1e-9)
            good = sum(c for b, c in zip(snap["buckets"], snap["counts"])
                       if b <= thresh)
            bad += snap["n"] - good
        for cname, clabels in o.bad_counters:
            v = self._registry.counter(cname, **clabels).value
            events += v
            bad += v
        return events, bad

    def tick(self, now: Optional[float] = None) -> None:
        """Take one cumulative sample and refresh the ``slo_*`` gauges."""
        ts = self._clock() if now is None else now
        sample = {o.name: self._sample_objective(o)
                  for o in self.objectives}
        with self._lock:
            self._ring.append((ts, sample))
            cutoff = ts - self.windows[-1] - 2 * self.tick_seconds
            drop = 0
            while drop < len(self._ring) - 2 \
                    and self._ring[drop][0] < cutoff:
                drop += 1
            if drop:
                del self._ring[:drop]
            if len(self._ring) > self._max_samples:
                del self._ring[:len(self._ring) - self._max_samples]
        for o in self.objectives:
            for w, st in self._window_stats(o.name, ts).items():
                wl = _wlabel(w)
                self._registry.gauge("slo_burn_rate", objective=o.name,
                                     window=wl).set(st["burn_rate"])
                self._registry.gauge(
                    "slo_attainment", objective=o.name,
                    window=wl).set(
                        st["attainment"] if st["attainment"] is not None
                        else 1.0)

    # -- queries --------------------------------------------------------------

    def _window_stats(self, name: str, now: Optional[float] = None
                      ) -> dict:
        o = next(ob for ob in self.objectives if ob.name == name)
        ts = self._clock() if now is None else now
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return {w: {"events": 0, "bad": 0, "attainment": None,
                        "burn_rate": 0.0} for w in self.windows}
        newest_ts, newest = ring[-1]
        out = {}
        for w in self.windows:
            cutoff = ts - w
            # base = the NEWEST sample at or before the cutoff (the
            # boundary sample just outside the window) so the delta
            # always spans at least the window — a window shorter than
            # the sampling cadence must measure a slightly longer span,
            # never read empty (burn 0 during an outage). Fall back to
            # the first sample ever: a young process's 1h window is its
            # whole lifetime.
            base = ring[0]
            for entry in ring:
                if entry[0] <= cutoff:
                    base = entry
                else:
                    break
            ev = newest.get(name, (0, 0))[0] - base[1].get(name, (0, 0))[0]
            bd = newest.get(name, (0, 0))[1] - base[1].get(name, (0, 0))[1]
            if ev <= 0:
                out[w] = {"events": 0, "bad": 0, "attainment": None,
                          "burn_rate": 0.0}
                continue
            frac_bad = max(0.0, min(1.0, bd / ev))
            out[w] = {
                "events": int(ev),
                "bad": int(bd),
                "attainment": 1.0 - frac_bad,
                "burn_rate": frac_bad / max(1e-9, 1.0 - o.target),
            }
        return out

    def worst_burn(self, window: Optional[float] = None) -> float:
        """The hottest burn rate across every objective at one window
        (default: the SHORTEST — the fast-burn signal the autoscaler
        folds into its grow/never-shrink decisions). 0.0 when no
        traffic has flowed."""
        w = self.windows[0] if window is None else float(window)
        ts = self._clock()
        worst = 0.0
        for o in self.objectives:
            st = self._window_stats(o.name, ts).get(w)
            if st is not None:
                worst = max(worst, float(st["burn_rate"]))
        return worst

    def status(self) -> dict:
        """The ``/debug/slo`` document: every declared objective with its
        per-window burn rates and attainment."""
        ts = self._clock()
        return {
            "windows_seconds": list(self.windows),
            "tick_seconds": self.tick_seconds,
            "objectives": [
                {
                    "name": o.name,
                    "latency_ms": o.latency_ms,
                    "target": o.target,
                    "histogram": o.histogram,
                    "windows": {_wlabel(w): st for w, st in
                                self._window_stats(o.name, ts).items()},
                }
                for o in self.objectives
            ],
        }

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Start the owned sampling thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.tick_seconds):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - monitor must not die
                    metrics.counter("slo_tick_errors_total").inc()

        self._thread = threading.Thread(target=loop, name="slo-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.tick_seconds + 1)


def _wlabel(w: float) -> str:
    return f"{int(w)}s"
