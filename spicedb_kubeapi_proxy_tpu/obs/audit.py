"""Structured decision audit log: one JSON line per authorization verdict.

The reference proxy's explainability story is "which rule allowed this?";
this module answers it durably: every DENY is always logged, ALLOWS are
rate-capped (a fleet list is thousands of identical allows per second —
the cap keeps the log a decision record, not a traffic mirror). Lines are
self-contained JSON objects:

    {"ts": <iso8601>, "decision": "allow"|"deny", "verb": ..,
     "resource": .., "subresource": .., "namespace": .., "name": ..,
     "subject": .., "groups": [..], "rule": <matched rule name(s)>,
     "reason": .., "cache_hit": bool|null, "revision": int|null,
     "trace_id": <hex>|null, "stages_us": {<span name>: <micros>, ..}}

``rule`` is the comma-joined names of the rules whose checks decided the
request (null before matching). ``stages_us`` carries the per-stage span
durations recorded so far on the request's trace (empty when tracing is
off). Destination is a file path (append, line-buffered) or the literal
``stderr``.
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time
from datetime import datetime, timezone
from typing import Optional

from ..utils.metrics import metrics

_CLOSE = object()  # writer-thread shutdown sentinel


class AuditLog:
    """Thread-safe decision writer with a token-bucket cap on allows.

    Lines drain through a BOUNDED queue on a dedicated writer thread:
    ``decision()`` is called synchronously from the proxy's event loop
    (the authz chain), and a slow or contended audit disk must add
    queue-put time there, never a write syscall — denies are uncapped
    by design, so a 403 storm against a throttled volume would
    otherwise stall every concurrent request. A full queue drops the
    line (counted in ``audit_dropped_total``) rather than blocking:
    the audit log records decisions, it does not gate them."""

    QUEUE_DEPTH = 4096

    def __init__(self, dest: str, allow_rps: float = 10.0,
                 clock=time.monotonic, shed_rps: Optional[float] = None):
        self.dest = dest
        self.allow_rps = float(allow_rps)
        # sheds get their own budget (default: the allow cap): an
        # overload sheds thousands/second by design, and the audit log
        # must record that it HAPPENED (agreeing with the trace ring on
        # every rejection path) without becoming a traffic mirror of the
        # very storm being shed
        self.shed_rps = float(allow_rps if shed_rps is None else shed_rps)
        self._clock = clock
        self._lock = threading.Lock()
        # burst = one second of allowance (min 1: a single allow must
        # always be loggable)
        self._burst = max(1.0, self.allow_rps)
        self._tokens = self._burst
        self._shed_burst = max(1.0, self.shed_rps)
        self._shed_tokens = self._shed_burst
        self._last = clock()
        self._shed_last = clock()
        if dest == "stderr":
            self._fh = sys.stderr
            self._owns = False
        else:
            self._fh = open(dest, "a", buffering=1)
            self._owns = True
        self._q: queue.Queue = queue.Queue(maxsize=self.QUEUE_DEPTH)
        self._writer = threading.Thread(
            target=self._drain, name="audit-writer", daemon=True)
        self._writer.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is _CLOSE:
                    return
                try:
                    self._fh.write(item)
                except (ValueError, OSError):
                    # closed/failed sink mid-shutdown: drop, never raise
                    metrics.counter("audit_dropped_total").inc()
            finally:
                self._q.task_done()

    def _take_allow(self) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self._burst,
                self._tokens + (now - self._last) * self.allow_rps)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def _take_shed(self) -> bool:
        with self._lock:
            now = self._clock()
            self._shed_tokens = min(
                self._shed_burst,
                self._shed_tokens + (now - self._shed_last) * self.shed_rps)
            self._shed_last = now
            if self._shed_tokens >= 1.0:
                self._shed_tokens -= 1.0
                return True
            return False

    def shed(self, *, op_class: str, tenant: str = "", verb: str = "",
             resource: str = "", retry_after: float = 0.0,
             reason: str = "", trace_id: Optional[str] = None) -> None:
        """One rate-capped line per admission shed — the rejection paths
        that never reach a verdict (so :meth:`decision` never sees them)
        still leave an audit record agreeing with the trace ring:
        ``{"decision": "shed", "class": .., "tenant": .., "retry_after":
        .., "trace_id": ..}``. Capped-out sheds are counted
        (``audit_sheds_sampled_out_total``), not logged."""
        if not self._take_shed():
            metrics.counter("audit_sheds_sampled_out_total").inc()
            return
        rec = {
            "ts": datetime.now(timezone.utc).isoformat(
                timespec="milliseconds"),
            "decision": "shed",
            "class": op_class,
            "tenant": tenant,
            "verb": verb,
            "resource": resource,
            "retry_after": round(float(retry_after), 3),
            "reason": reason,
            "trace_id": trace_id,
        }
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            self._q.put_nowait(line)
        except queue.Full:
            metrics.counter("audit_dropped_total").inc()
            return
        metrics.counter("audit_decisions_total", decision="shed").inc()

    def decision(self, *, allow: bool, verb: str = "", resource: str = "",
                 subresource: str = "", namespace: str = "", name: str = "",
                 subject: str = "", groups: Optional[list] = None,
                 rule: Optional[str] = None, reason: str = "",
                 cache_hit: Optional[bool] = None,
                 revision: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 stages_us: Optional[dict] = None) -> None:
        """Write one decision line. Denies always; allows only while the
        rate cap has budget (capped-out allows are counted, not logged)."""
        if allow and not self._take_allow():
            metrics.counter("audit_allows_sampled_out_total").inc()
            return
        rec = {
            "ts": datetime.now(timezone.utc).isoformat(
                timespec="milliseconds"),
            "decision": "allow" if allow else "deny",
            "verb": verb,
            "resource": resource,
            "subresource": subresource,
            "namespace": namespace,
            "name": name,
            "subject": subject,
            "groups": list(groups or ()),
            "rule": rule,
            "reason": reason,
            "cache_hit": cache_hit,
            "revision": revision,
            "trace_id": trace_id,
            "stages_us": dict(stages_us or {}),
        }
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            self._q.put_nowait(line)
        except queue.Full:
            metrics.counter("audit_dropped_total").inc()
            return
        metrics.counter("audit_decisions_total",
                        decision=rec["decision"]).inc()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued line has been written (tests,
        shutdown); False when ``timeout`` expired first. Bounded waits
        matter at shutdown: a wedged sink (stale NFS mount, blocked
        pipe) must not turn SIGTERM into a hang."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                self._q.all_tasks_done.wait(left)
        return True

    def close(self, timeout: float = 5.0) -> None:
        if not self.flush(timeout):
            metrics.counter("audit_dropped_total").inc(
                self._q.unfinished_tasks)
        try:
            self._q.put_nowait(_CLOSE)
        except queue.Full:
            pass  # daemon writer dies with the process
        self._writer.join(timeout=timeout)
        if self._owns and not self._writer.is_alive():
            try:
                self._fh.close()
            except OSError:
                pass
