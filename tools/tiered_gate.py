#!/usr/bin/env python
"""CI regression gate for tiered graph storage (ISSUE 18).

Reads a bench.py result JSON (argument path or stdin) and enforces the
hardware-independent tiering invariants:

1. **Steady-state streaming never recompiles.** The demand key is a
   pure function of query shape, so repeated hot-working-set traffic
   must reuse its trace (``tiered.zero_recompiles``). A recompile means
   residency leaked into the jit signature.

2. **The hot working set tracks the all-resident baseline.** The gate
   is the RATIO of the 50%-budget steady-state check p50 to the same
   run's all-resident p50 — internal to one run, so it holds on any
   backend speed. Once the demanded blocks are admitted, a dispatch
   pays only the tier lookup; the ratio must stay under
   ``TIERED_RATIO`` (default 1.3).

3. **Beyond-budget answers are still the oracle's.** Both the hot
   point and the beyond-budget point (budget far under the working
   set, every dispatch streaming) must report ``parity_ok``, and the
   beyond-budget point must have actually paid miss stalls — an empty
   stall count means the phase silently measured a resident graph.

Exit 0 on pass, 1 with a named reason on fail, 2 on malformed input.
"""

from __future__ import annotations

import json
import os
import sys

MAX_RATIO = float(os.environ.get("TIERED_RATIO", "1.3"))


def main() -> int:
    if len(sys.argv) > 1:
        with open(sys.argv[1]) as f:
            raw = f.read()
    else:
        raw = sys.stdin.read()
    # bench.py's contract is exactly one JSON line on stdout, but be
    # lenient about surrounding log noise: take the last parseable line
    result = None
    for line in reversed(raw.strip().splitlines()):
        try:
            result = json.loads(line)
            break
        except ValueError:
            continue
    if not isinstance(result, dict):
        print("tiered gate: no JSON result found", file=sys.stderr)
        return 2
    if result.get("error"):
        print(f"tiered gate: bench errored: {result['error']}",
              file=sys.stderr)
        return 2

    t = result.get("tiered")
    if not isinstance(t, dict):
        print("tiered gate: result carries no tiered block (bench too "
              "old, or the phase was skipped)", file=sys.stderr)
        return 1
    failures = []
    if not t.get("zero_recompiles"):
        failures.append(
            "steady-state streaming re-traced the fixpoint (expected "
            "zero recompiles: residency must stay out of the jit key)")
    if not t.get("parity_ok"):
        failures.append("hot-point answers diverged from the "
                        "all-resident oracle")
    ratio = t.get("tiered_over_resident")
    p_t = t.get("tiered_check_p50_ms")
    p_r = t.get("resident_check_p50_ms")
    if ratio is None or not p_r:
        failures.append("missing tiered_over_resident / "
                        "resident_check_p50_ms")
    else:
        verdict = "OK" if ratio <= MAX_RATIO else "FAIL"
        print(f"tiered gate: hot-working-set {p_t:.2f}ms / "
              f"all-resident {p_r:.2f}ms = {ratio:.2f}x "
              f"(limit {MAX_RATIO}x) [{verdict}]")
        if ratio > MAX_RATIO:
            failures.append(
                f"hot-working-set p50 is {ratio:.2f}x the all-resident "
                f"p50 (limit {MAX_RATIO}x): admitted blocks are paying "
                "more than the tier lookup again")
    bb = t.get("beyond_budget")
    if not isinstance(bb, dict):
        failures.append("missing beyond_budget point")
    else:
        if not bb.get("parity_ok"):
            failures.append("beyond-budget answers diverged from the "
                            "oracle")
        if not bb.get("miss_stalls"):
            failures.append(
                "beyond-budget point recorded no miss stalls: the "
                "graph never actually streamed")
    if failures:
        for f_ in failures:
            print(f"tiered gate FAIL: {f_}", file=sys.stderr)
        return 1
    print(f"tiered gate PASS: {t.get('hot_blocks')}/"
          f"{t.get('hot_blocks', 0) + t.get('cold_blocks', 0)} blocks "
          f"hot under {t.get('budget_bytes')}B budget, "
          f"{bb.get('miss_stalls')} beyond-budget stalls, 0 recompiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
