#!/usr/bin/env python
"""CI regression gate for the write path (delta overlay, ISSUE 8).

Reads a bench.py result JSON (argument path or stdin) and enforces the
two hardware-independent write-path invariants:

1. **Zero full recompiles in the steady-state churn loop.** The
   measured write->read pairs run against pre-existing objects, so every
   write must be absorbed by the device-resident overlay
   (``read_after_write.recompiles == 0``). A single recompile means the
   incremental path silently regressed to the per-write re-encode.

2. **Read-after-write tracks the read-only dispatch.** The gate is the
   RATIO of fully-consistent read-after-write p50 to the same run's
   read-only list-filter p50 — a quantity internal to one run, so it
   holds on any backend speed. The recorded seed (BENCH_r05, before the
   overlay) sat at 3.43ms / 1.59ms = **2.16x**: every write paid a
   host-side re-encode before the next query could dispatch. With the
   overlay a write adds only an O(write) append, so the ratio must stay
   under ``WRITE_PATH_RATIO`` (default 1.8 — comfortably below the
   seed's 2.16, comfortably above measurement jitter).

Exit 0 on pass, 1 with a named reason on fail, 2 on malformed input.
"""

from __future__ import annotations

import json
import os
import sys

MAX_RATIO = float(os.environ.get("WRITE_PATH_RATIO", "1.8"))


def main() -> int:
    if len(sys.argv) > 1:
        with open(sys.argv[1]) as f:
            raw = f.read()
    else:
        raw = sys.stdin.read()
    # bench.py's contract is exactly one JSON line on stdout, but be
    # lenient about surrounding log noise: take the last parseable line
    result = None
    for line in reversed(raw.strip().splitlines()):
        try:
            result = json.loads(line)
            break
        except ValueError:
            continue
    if not isinstance(result, dict):
        print("write-path gate: no JSON result found", file=sys.stderr)
        return 2
    if result.get("error"):
        print(f"write-path gate: bench errored: {result['error']}",
              file=sys.stderr)
        return 2

    raw_block = result.get("read_after_write")
    if not isinstance(raw_block, dict):
        print("write-path gate: result carries no read_after_write "
              "block (bench too old, or the phase was skipped)",
              file=sys.stderr)
        return 1
    failures = []
    recompiles = raw_block.get("recompiles")
    if recompiles != 0:
        failures.append(
            f"{recompiles} full recompile(s) during steady-state write "
            "churn (expected 0: every write must ride the delta overlay)")
    p50_raw = result.get("p50_read_after_write_ms")
    p50_read = result.get("p50_wall_ms")
    if not p50_raw or not p50_read:
        failures.append("missing p50_read_after_write_ms / p50_wall_ms")
    else:
        ratio = p50_raw / p50_read
        verdict = "OK" if ratio <= MAX_RATIO else "FAIL"
        print(f"write-path gate: read-after-write {p50_raw:.2f}ms / "
              f"read-only {p50_read:.2f}ms = {ratio:.2f}x "
              f"(limit {MAX_RATIO}x, seed was 2.16x) [{verdict}]")
        if ratio > MAX_RATIO:
            failures.append(
                f"read-after-write p50 is {ratio:.2f}x the read-only "
                f"p50 (limit {MAX_RATIO}x): the write path is paying "
                "more than an overlay append again")
    if failures:
        for f_ in failures:
            print(f"write-path gate FAIL: {f_}", file=sys.stderr)
        return 1
    print(f"write-path gate PASS: {raw_block.get('incremental_updates')} "
          "overlay updates, 0 recompiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
