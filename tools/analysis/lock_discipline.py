"""lock-discipline: what may happen while a named lock is held, and
which shared structures may only be iterated under one.

The PR 5 review class: an unlocked iteration over the admission
controller's tenant dict raced concurrent releases; a double-release
needed an idempotence gate under the lock. Statically enforced here:

- no ``await`` inside a sync ``with <lock>:`` body — the lock spans an
  arbitrary number of loop turns and every other acquirer (including
  worker threads feeding the loop) deadlocks behind it
- no blocking IO (sleep, fsync, subprocess, blocking connect) or device
  synchronization (``block_until_ready``, ``jax.device_put``) while a
  named lock is held — hold times bound every other thread's tail
  latency (the sanitizer's hold-time ceiling is the runtime twin)
- iteration over shared registries (tenant/peer/subscriber/stream
  dicts) must happen inside a lockish ``with`` in the same function, or
  over an explicit snapshot (``list(...)``/``tuple(...)``/``.copy()``
  taken under one — snapshots taken outside any lock are still flagged)

Lockish = a ``with`` context whose terminal name contains lock/guard/
mutex (``self._lock``, ``host_lock``, ``cg._host_guard()``).
``async with`` (asyncio locks) is exempt: awaiting under one is its
design.
"""

from __future__ import annotations

import ast

from .core import (Module, call_name, terminal_attr, with_lock_items,
                   held_lock_names)

RULE = "lock-discipline"

BLOCKING_UNDER_LOCK = {
    "time.sleep": "blocking sleep",
    "os.fsync": "blocking fsync",
    "os.fdatasync": "blocking fsync",
    "socket.create_connection": "blocking connect",
    "subprocess.run": "subprocess wait",
    "subprocess.check_output": "subprocess wait",
    "subprocess.check_call": "subprocess wait",
    "jax.device_put": "device transfer",
}

BLOCKING_METHODS = {
    "block_until_ready": "device sync",
    "fsync": "blocking fsync",
}

# shared registries the review rounds locked by hand: iterating them
# unlocked races concurrent insert/delete (RuntimeError: dict changed
# size) or observes torn state
SHARED_DICTS = ("_tenants", "_peers", "_subs", "_subscribers",
                "_streams", "_waiters", "_flights", "_sessions",
                "_followers", "_watchers")

SNAPSHOT_CALLS = ("list", "tuple", "dict", "set", "sorted")


def _in_lock_body(mod: Module, node: ast.AST) -> bool:
    return bool(held_lock_names(mod, node))


def _check_with_lock(mod: Module, with_node: ast.With, findings: list):
    lock_names = [terminal_attr(e) or "?"
                  for e in with_lock_items(with_node)]
    if not lock_names:
        return
    lock = lock_names[0]
    stack = list(with_node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            findings.append(mod.finding(
                RULE, n, f"await-under-{lock}",
                f"await while holding `{lock}` — the lock spans loop "
                f"turns; every other acquirer (threads included) stalls "
                f"behind it"))
            continue
        if isinstance(n, ast.Call):
            name = call_name(n)
            matched = False
            if name is not None:
                for pat, why in BLOCKING_UNDER_LOCK.items():
                    if name == pat or name.endswith("." + pat):
                        findings.append(mod.finding(
                            RULE, n, f"{pat}-under-{lock}",
                            f"{why} `{name}(...)` while holding "
                            f"`{lock}` — move it outside the critical "
                            f"section"))
                        matched = True
                        break
            if not matched and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in BLOCKING_METHODS:
                findings.append(mod.finding(
                    RULE, n, f"{n.func.attr}-under-{lock}",
                    f"{BLOCKING_METHODS[n.func.attr]} `.{n.func.attr}()` "
                    f"while holding `{lock}`"))
        stack.extend(ast.iter_child_nodes(n))


def _shared_dict_name(expr: ast.AST):
    """The shared-registry name if *expr* reads one: ``self._tenants``,
    ``self._tenants.items()``, etc."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr in ("items", "keys", "values"):
        expr = expr.func.value
    name = terminal_attr(expr)
    return name if name in SHARED_DICTS else None


def _check_shared_iteration(mod: Module, findings: list):
    for node in ast.walk(mod.tree):
        iters = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters = [node.iter]
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters = [g.iter for g in node.generators]
        for it in iters:
            name = _shared_dict_name(it)
            if name is None:
                continue
            if _in_lock_body(mod, it):
                continue
            findings.append(mod.finding(
                RULE, it, f"unlocked-iter-{name}",
                f"iteration over shared `{name}` outside any lock — "
                f"a concurrent insert/delete tears it (snapshot under "
                f"the lock, iterate the copy)"))
        # snapshot calls over shared dicts outside any lock
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in SNAPSHOT_CALLS and node.args:
            name = _shared_dict_name(node.args[0])
            if name is not None and not _in_lock_body(mod, node):
                findings.append(mod.finding(
                    RULE, node, f"unlocked-snapshot-{name}",
                    f"snapshot of shared `{name}` outside any lock — "
                    f"the copy itself can observe a resize"))


def run(modules) -> list:
    findings = []
    for mod in modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.With):
                _check_with_lock(mod, node, findings)
        _check_shared_iteration(mod, findings)
    return findings
