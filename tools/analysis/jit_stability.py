"""jit-stability: the zero-recompile / no-host-sync contracts from the
delta-overlay and caveat PRs (8/9), as static checks.

A jitted function re-specializes on every new *static* argument value
and every Python-level branch on a traced value is a trace error (or a
silent constant). The write path's contract is ZERO recompiles under
steady churn — so the traced functions must keep Python out of the hot
signature:

- traced parameters (not partial-bound, not in ``static_argnums`` /
  ``static_argnames``) must not drive Python control flow: used as an
  ``if``/``while`` test, compared in one, or passed to ``range()`` —
  each is either a TracerBoolConversionError at runtime or a hidden
  re-specialization
- DERIVED traced values must not either (the ISSUE 17 semiring
  contract: the per-iteration push/pull switch is a ``lax.cond`` on
  traced occupancy, never a Python ``if``): locals assigned —
  transitively, to a small fixpoint — from traced parameters taint
  their targets, and an ``if``/``while`` test on a tainted name is a
  finding. Static-shape extractors (``.shape`` / ``.ndim`` /
  ``.dtype`` / ``.size`` attribute reads, ``len()``) do NOT propagate
  taint (they are Python ints under trace), and pure identity guards
  (``x is None`` / ``x is not None``) are allowed — tracers have
  stable identity
- no ``numpy`` (``np.*``) calls applied directly to traced parameters —
  numpy eagerly concretizes, forcing a device sync per call (use
  ``jnp``/``lax``)
- no ``.item()`` inside a jitted body (concretization error on tracers)
- no host synchronization while holding a lock, anywhere in the repo:
  ``.item()`` / ``jax.device_get`` under a ``with <lock>:`` serializes
  every other thread behind a device round-trip (the PR 8 host_lock
  rule: snapshot under the lock, sync outside it)

Jitted functions are found by name: ``jax.jit(f)``, ``jax.jit(
partial(f, bound...))`` (the bound prefix AND keyword-bound names are
static), ``pjit`` same, ``@jax.jit``-style decorators, and — the mesh
path (parallel/sharded.py, recompile-free appends) — targets resolved
through local ``Name = ...`` assignments and ``shard_map``-family
wrappers: ``fn = partial(f, meta, k=K); smapped = shard_map(fn, ...);
jax.jit(smapped)`` checks ``f`` with ``meta``/``k`` static.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Module, call_name, dotted_name, held_lock_names

RULE = "jit-stability"

JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit")

# transparent wrappers whose first argument is the traced function —
# jit(shard_map(f)) must check f, not give up at the wrapper
WRAP_NAMES = ("shard_map", "jax.shard_map",
              "jax.experimental.shard_map.shard_map", "smap")


def _resolve_target(expr, assigns: Dict[str, ast.AST], depth: int = 0
                    ) -> Optional[Tuple[str, int, Set[str]]]:
    """(function name, partial-bound positional count, partial-bound
    keyword names) for a jit target expression, chased through Name
    assignments, shard_map-family wrappers, and (nested) partials."""
    if expr is None or depth > 6:
        return None
    if isinstance(expr, ast.Name):
        nxt = assigns.get(expr.id)
        if nxt is None:
            return expr.id, 0, set()
        return _resolve_target(nxt, assigns, depth + 1)
    if isinstance(expr, ast.Call):
        name = call_name(expr)
        if name in WRAP_NAMES and expr.args:
            return _resolve_target(expr.args[0], assigns, depth + 1)
        if name in ("partial", "functools.partial") and expr.args:
            inner = _resolve_target(expr.args[0], assigns, depth + 1)
            if inner is None:
                return None
            fname, bound, kws = inner
            return (fname, bound + len(expr.args) - 1,
                    kws | {kw.arg for kw in expr.keywords
                           if kw.arg is not None})
    return None


def _jit_call_target(call: ast.Call, assigns: Dict[str, ast.AST]
                     ) -> Optional[Tuple[str, int, Set[str]]]:
    """(function name, bound positional count, bound keyword names) when
    *call* is ``jax.jit(f)`` / ``jax.jit(partial(f, a, b, k=v))`` /
    ``jax.jit(<name assigned from shard_map(partial(f, ...))>)``."""
    name = call_name(call)
    if name not in JIT_NAMES or not call.args:
        return None
    return _resolve_target(call.args[0], assigns)


def _static_names(call: ast.Call, func: ast.FunctionDef,
                  bound: int, bound_kws: Set[str] = frozenset()
                  ) -> Set[str]:
    """Parameter names jit treats as static: partial-bound positional
    prefix, partial keyword-bound names, plus static_argnums /
    static_argnames keywords."""
    params = [a.arg for a in func.args.args]
    static = set(params[:bound]) | set(bound_kws)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    static.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    idx = n.value
                    if 0 <= idx < len(params):
                        static.add(params[idx])
    return static


def _decorated_jit(func: ast.FunctionDef) -> Optional[ast.Call]:
    """A synthetic call node carrying static_arg* kwargs when *func* is
    decorated with jit; bare ``@jax.jit`` yields an empty one."""
    for dec in func.decorator_list:
        if isinstance(dec, ast.Call):
            dname = call_name(dec)
            if dname in JIT_NAMES:
                return dec
            if dname in ("partial", "functools.partial") and dec.args \
                    and call_name(dec.args[0]) in JIT_NAMES:
                synth = ast.Call(func=dec.args[0], args=[],
                                 keywords=dec.keywords)
                return synth
        elif dotted_name(dec) in JIT_NAMES:
            return ast.Call(func=dec, args=[], keywords=[])
    return None


def _name_refs(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


# attribute reads that yield static Python values even on tracers — they
# must not propagate taint (``if v.shape[0] > 4`` is specialization-free)
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")


def _dynamic_refs(expr: ast.AST) -> Set[str]:
    """Names referenced by *expr* through value-carrying paths only:
    subtrees under a static-shape attribute read or a ``len()`` call are
    skipped — their results are Python scalars under trace."""
    out: Set[str] = set()

    def rec(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Call) and call_name(n) == "len":
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for c in ast.iter_child_nodes(n):
            rec(c)

    rec(expr)
    return out


def _is_identity_test(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` style guards: identity on a
    tracer is a stable Python fact, not a concretization."""
    return isinstance(test, ast.Compare) and bool(test.ops) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


def _derived_traced(func, traced: Set[str]) -> Set[str]:
    """Locals tainted by traced parameters: names assigned from
    expressions that reference traced-or-tainted names through a dynamic
    path, chased to a bounded fixpoint (assignment order in source need
    not match dataflow order). Inner defs are skipped to mirror the body
    walk in :func:`_check_jitted`."""
    assigns = []
    stack = list(func.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)) \
                and n.value is not None:
            assigns.append(n)
        stack.extend(ast.iter_child_nodes(n))
    tainted: Set[str] = set()
    for _ in range(10):
        changed = False
        for a in assigns:
            if not (_dynamic_refs(a.value) & (traced | tainted)):
                continue
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            for t in targets:
                for nn in ast.walk(t):
                    if isinstance(nn, ast.Name) and nn.id not in traced \
                            and nn.id not in tainted:
                        tainted.add(nn.id)
                        changed = True
        if not changed:
            break
    return tainted


def _check_jitted(mod: Module, func, static: Set[str],
                  findings: list) -> None:
    params = {a.arg for a in func.args.args} | \
        {a.arg for a in func.args.kwonlyargs}
    traced = params - static
    tainted = _derived_traced(func, traced)
    stack = list(func.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # inner defs are traced closures; checked via walk
        if isinstance(n, (ast.If, ast.While)):
            if _is_identity_test(n.test):
                refs = set()  # `is None` guards: tracer identity is stable
            else:
                refs = _dynamic_refs(n.test)
            for u in sorted(refs & traced):
                findings.append(mod.finding(
                    RULE, n, f"py-branch-{u}",
                    f"jitted `{func.name}` branches in Python on traced "
                    f"arg `{u}` — a trace error or per-value "
                    f"re-specialization; use lax.cond/select or declare "
                    f"it static"))
            for u in sorted((refs & tainted) - traced):
                    findings.append(mod.finding(
                        RULE, n, f"py-branch-derived-{u}",
                        f"jitted `{func.name}` branches in Python on "
                        f"`{u}`, derived from a traced arg — the branch "
                        f"bakes one side into the trace (the semiring "
                        f"push/pull switch must be a lax.cond on the "
                        f"traced value)"))
        if isinstance(n, ast.Call):
            cname = call_name(n)
            if cname == "range":
                used = set()
                for a in n.args:
                    used |= _name_refs(a) & traced
                for u in sorted(used):
                    findings.append(mod.finding(
                        RULE, n, f"py-range-{u}",
                        f"jitted `{func.name}` drives range() with "
                        f"traced arg `{u}` — the loop length "
                        f"re-specializes per value; use lax.fori_loop "
                        f"or make it static"))
            elif cname is not None and (cname.startswith("np.")
                                        or cname.startswith("numpy.")):
                used = set()
                for a in list(n.args) + [k.value for k in n.keywords]:
                    if isinstance(a, ast.Name) and a.id in traced:
                        used.add(a.id)
                for u in sorted(used):
                    findings.append(mod.finding(
                        RULE, n, f"np-on-traced-{u}",
                        f"jitted `{func.name}` applies `{cname}` to "
                        f"traced arg `{u}` — numpy concretizes (device "
                        f"sync / trace error); use jnp"))
            if isinstance(n.func, ast.Attribute) and n.func.attr == "item" \
                    and not n.args:
                findings.append(mod.finding(
                    RULE, n, "item-in-jit",
                    f"`.item()` inside jitted `{func.name}` — "
                    f"concretization of a tracer"))
        stack.extend(ast.iter_child_nodes(n))


def _check_host_sync_under_lock(mod: Module, findings: list) -> None:
    for n in ast.walk(mod.tree):
        if not isinstance(n, ast.Call):
            continue
        token = None
        if isinstance(n.func, ast.Attribute) and n.func.attr == "item" \
                and not n.args:
            token = ".item()"
        else:
            cname = call_name(n)
            if cname is not None and cname.endswith("device_get"):
                token = "device_get"
        if token is None:
            continue
        held = held_lock_names(mod, n)
        if held:
            findings.append(mod.finding(
                RULE, n, f"host-sync-under-{held[0]}",
                f"host sync `{token}` while holding `{held[0]}` — every "
                f"other thread serializes behind a device round-trip; "
                f"snapshot under the lock, sync outside"))


def run(modules) -> list:
    findings = []
    for mod in modules:
        if mod.tree is None:
            continue
        funcs: Dict[str, ast.FunctionDef] = {}
        # SCOPE-AWARE assignment maps: the same local name (`fn`,
        # `smapped`) assigned in two different functions must resolve
        # per enclosing scope — a module-wide map would let the first
        # function's assignment shadow every later one and silently
        # skip (or mis-static) their jit targets. Within one scope the
        # first assignment wins (the shard_map check_vma/check_rep
        # fallback pair targets the same traced function either way).
        scope_assigns: Dict[Optional[ast.AST], Dict[str, ast.AST]] = {}
        _FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.FunctionDef):
                funcs.setdefault(n.name, n)
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                scope = next((a for a in mod.ancestors(n)
                              if isinstance(a, _FUNCS)), None)
                scope_assigns.setdefault(scope, {}).setdefault(
                    n.targets[0].id, n.value)

        def assigns_for(call: ast.Call) -> Dict[str, ast.AST]:
            scopes = [a for a in mod.ancestors(call)
                      if isinstance(a, _FUNCS)]
            eff = dict(scope_assigns.get(None, {}))
            for sc in reversed(scopes):  # outermost first: inner shadows
                eff.update(scope_assigns.get(sc, {}))
            return eff

        jit_sites: List[Tuple[ast.Call, str, int, Set[str]]] = []
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call):
                tgt = _jit_call_target(n, assigns_for(n))
                if tgt is not None:
                    jit_sites.append((n, tgt[0], tgt[1], tgt[2]))
        seen: Set[str] = set()
        for call, fname, bound, bound_kws in jit_sites:
            func = funcs.get(fname)
            if func is None or fname in seen:
                continue
            seen.add(fname)
            _check_jitted(mod, func,
                          _static_names(call, func, bound, bound_kws),
                          findings)
        for fname, func in funcs.items():
            if fname in seen:
                continue
            dec = _decorated_jit(func)
            if dec is not None:
                _check_jitted(mod, func, _static_names(dec, func, 0),
                              findings)
        _check_host_sync_under_lock(mod, findings)
    return findings
