"""loop-blocking: no blocking calls inside ``async def`` bodies.

The event loop serves every in-flight request, heartbeat, and watch
stream; one blocking call stalls them all (the PR 12 class: a chaos
delay armed at a loop-side failpoint turned a brownout into spurious
elections). Flagged inside async bodies (nested sync defs are skipped —
they run in executors via ``asyncio.to_thread``/``run_in_executor``):

- ``time.sleep`` and friends (the canonical offender)
- blocking sqlite (``sqlite3.connect``), ``os.fsync``, subprocess waits,
  blocking socket construction
- non-awaited ``.get()``/``.put()`` on queue-shaped receivers (a
  ``queue.Queue`` on the loop parks the whole process; ``asyncio.Queue``
  calls are awaited and therefore exempt)
- device-dispatch synchronization (``.block_until_ready()``) — a device
  round-trip on the loop thread is a hidden multi-ms stall
"""

from __future__ import annotations

import ast

from .core import Module, call_name, terminal_attr

RULE = "loop-blocking"

BLOCKING_CALLS = {
    "time.sleep": "blocking sleep",
    "os.fsync": "blocking fsync",
    "os.fdatasync": "blocking fsync",
    "sqlite3.connect": "blocking sqlite open",
    "socket.create_connection": "blocking connect",
    "subprocess.run": "subprocess wait",
    "subprocess.check_output": "subprocess wait",
    "subprocess.check_call": "subprocess wait",
    "subprocess.call": "subprocess wait",
}

BLOCKING_METHODS = {
    "block_until_ready": "device sync",
    "fsync": "blocking fsync",
}

# sqlite on db-shaped receivers: commit fsyncs on real files (the dtx
# event log under --data-dir), and even reads serialize on the
# connection lock
SQLITE_METHODS = ("execute", "executemany", "executescript", "commit")
DBISH = ("db", "_db", "conn", "_conn", "cur", "cursor", "_cursor",
         "dbconn")

QUEUEISH = ("queue", "_q")


def _dbish(recv: ast.AST) -> bool:
    name = terminal_attr(recv)
    return name is not None and name.lower() in DBISH


def _queueish(recv: ast.AST) -> bool:
    name = terminal_attr(recv)
    if name is None:
        return False
    low = name.lower()
    return "queue" in low or low == "q" or low.endswith("_q")


def _is_awaited(mod: Module, call: ast.Call) -> bool:
    """Awaited directly, or wrapped in an awaited expression such as
    ``await asyncio.wait_for(q.get(), ...)`` — an asyncio.Queue
    coroutine, not a blocking call."""
    for anc in mod.ancestors(call):
        if isinstance(anc, ast.Await):
            return True
        if isinstance(anc, ast.stmt):
            return False
    return False


def _check_call(mod: Module, call: ast.Call, out: list) -> None:
    name = call_name(call)
    if name is not None:
        # match both "time.sleep" and "sleep" imported bare won't match —
        # bare `sleep(...)` is caught by the suffix check below
        for pat, why in BLOCKING_CALLS.items():
            if name == pat or name.endswith("." + pat):
                out.append(mod.finding(
                    RULE, call, pat,
                    f"{why} `{name}(...)` on the event loop — use "
                    f"asyncio.to_thread / loop.run_in_executor"))
                return
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        if meth in SQLITE_METHODS and _dbish(call.func.value):
            out.append(mod.finding(
                RULE, call, f"sqlite.{meth}",
                f"blocking sqlite `.{meth}()` in an async body — a "
                f"commit fsyncs on real files; run the DB op via "
                f"asyncio.to_thread (the connection must be "
                f"check_same_thread=False and lock-serialized)"))
            return
        if meth in BLOCKING_METHODS:
            out.append(mod.finding(
                RULE, call, meth,
                f"{BLOCKING_METHODS[meth]} `.{meth}()` on the event "
                f"loop — dispatch from a worker thread"))
            return
        if meth in ("get", "put") and _queueish(call.func.value) \
                and not _is_awaited(mod, call):
            # block=False / get_nowait-style kwargs make it non-blocking
            for kw in call.keywords:
                if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return
            out.append(mod.finding(
                RULE, call, f"queue.{meth}",
                f"non-awaited queue `.{meth}()` in an async body can "
                f"park the event loop — await an asyncio.Queue or move "
                f"to a worker thread"))


def run(modules) -> list:
    findings = []
    for mod in modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            stack = list(node.body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue  # separate execution context
                if isinstance(n, ast.Call):
                    _check_call(mod, n, findings)
                stack.extend(ast.iter_child_nodes(n))
    return findings
