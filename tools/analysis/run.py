#!/usr/bin/env python
"""Invariant lint suite runner.

    python tools/analysis/run.py [--strict] [--select RULE,...] [paths]

Runs the five AST passes (loop-blocking, lock-discipline, fail-closed,
jit-stability, metrics-contract) over the package (default:
``spicedb_kubeapi_proxy_tpu``). Findings matching
``tools/analysis/allowlist.txt`` — fingerprints with a mandatory
one-line justification — are reported as allowlisted; everything else
is new. ``--strict`` (the CI gate, ``make analyze``) exits non-zero on
any new finding or malformed allowlist entry.
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.analysis import (core, fail_closed, jit_stability,  # noqa: E402
                            lock_discipline, loop_blocking,
                            metrics_contract)

PASSES = {
    loop_blocking.RULE: lambda mods, root: loop_blocking.run(mods),
    lock_discipline.RULE: lambda mods, root: lock_discipline.run(mods),
    fail_closed.RULE: lambda mods, root: fail_closed.run(mods),
    jit_stability.RULE: lambda mods, root: jit_stability.run(mods),
    metrics_contract.RULE:
        lambda mods, root: metrics_contract.run(mods, root),
}

DEFAULT_PATHS = ("spicedb_kubeapi_proxy_tpu",)
DEFAULT_ALLOWLIST = os.path.join("tools", "analysis", "allowlist.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    ap.add_argument("--root", default=_ROOT)
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any unallowlisted finding")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass names (default: all)")
    ap.add_argument("--allowlist", default=None,
                    help=f"allowlist path (default {DEFAULT_ALLOWLIST} "
                         f"under --root; empty string disables)")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for name in PASSES:
            print(name)
        return 0

    selected = list(PASSES)
    if args.select:
        selected = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in selected if s not in PASSES]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)}; "
                  f"available: {', '.join(PASSES)}", file=sys.stderr)
            return 2

    al_path = args.allowlist
    if al_path is None:
        al_path = os.path.join(args.root, DEFAULT_ALLOWLIST)
    allow = (core.Allowlist() if al_path == ""
             else core.Allowlist.load(al_path))

    paths = args.paths or list(DEFAULT_PATHS)
    modules = core.load_modules(args.root, paths)
    findings = []
    for mod in modules:
        if mod.tree is None:
            findings.append(core.Finding(
                rule="parse", path=mod.path,
                line=mod.syntax_error.lineno or 0, scope="<module>",
                token="syntax-error",
                message=f"does not parse: {mod.syntax_error.msg}"))
    for name in selected:
        findings.extend(PASSES[name](modules, args.root))

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.token))
    new, allowed = [], []
    for f in findings:
        (allowed if allow.match(f) else new).append(f)

    for f in new:
        print(f.render())
    if allowed:
        print(f"-- {len(allowed)} allowlisted finding(s) "
              f"(tools/analysis/allowlist.txt)")
    for entry in allow.malformed:
        print(f"allowlist: malformed entry (needs "
              f"`rule|path|scope|token  # justification`): {entry}",
              file=sys.stderr)
    stale = allow.stale()
    if stale:
        print(f"-- {len(stale)} stale allowlist entr"
              f"{'y' if len(stale) == 1 else 'ies'} (no longer "
              f"matched — prune when convenient):")
        for fp in stale:
            print(f"   {fp}")

    print(f"analysis: {len(modules)} files, "
          f"{len(new)} new / {len(allowed)} allowlisted finding(s), "
          f"passes: {', '.join(selected)}")
    if args.strict and (new or allow.malformed):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
