"""fail-closed: the authorization chain may never swallow a failure.

Scope is the decision path — ``authz/middleware.py`` plus the engine
dispatch surfaces (``engine/remote.py``, ``engine/engine.py``,
``scaleout/planner.py``): the modules where an eaten exception is a
fail-open verdict or a silent half-answer (the chaos campaign's
never-fail-open invariant, PR 12).

Two checks:

1. every ``except`` handler in scope must visibly dispose of the
   failure — re-``raise``, raise *something* (the DependencyUnavailable
   family feeds the shared 503 builder), call/return through
   ``_fail_closed_503``, or ``return``/``continue``/``break`` an
   explicit fallback value. Handlers that fall through with only
   logging/metrics are findings (allowlist the intentional best-effort
   cleanup paths with a justification).
An ``except`` line (or its first body line) carrying a REASONED
suppression comment — ``# noqa: BLE001 - <why>`` — is an in-code
justification and is honored (a bare ``noqa`` without a reason is not).
``parser.error(...)`` / ``sys.exit(...)`` count as disposal: both raise.

2. every Retry-After producer must clamp: a ``headers["Retry-After"]``
   assignment outside the shared ``_fail_closed_503`` builder, or one
   whose value expression doesn't clamp via ``min(RETRY_AFTER_CAP_S``,
   is a finding — an unbounded hint parks polite clients forever
   (PR 12 satellite).
"""

from __future__ import annotations

import ast
import re

from .core import Module, call_name

RULE = "fail-closed"

SCOPE_FILES = (
    "authz/middleware.py",
    "engine/remote.py",
    "engine/engine.py",
    "scaleout/planner.py",
    # the tuple mover's routing/cutover path: a swallowed failure here
    # is a half-routed placement serving stale verdicts
    "scaleout/rebalance.py",
    # the live schema migrator: a swallowed failure mid-backfill or
    # mid-cut leaves two graphs half-routed against one schema
    "migration/migrator.py",
    # the frontier exchange must under-approximate on ANY failure —
    # a swallowed expansion error that defaulted a verdict open would
    # grant across a shard boundary nobody proved
    "scaleout/frontier.py",
    # the autoscale controller acts on the live shard map: a swallowed
    # apply failure must count + leave the fleet untouched, never
    # half-start a transition
    "autoscale/controller.py",
)

BUILDER = "_fail_closed_503"
CLAMP_NAME = "RETRY_AFTER_CAP_S"

# calls that never return: argparse's .error() and sys.exit both raise.
# .error is recognized ONLY on parser-shaped receivers — log.error is
# logging, not disposal
TERMINAL_CALLS = ("sys.exit", "os._exit", "ap.error", "parser.error",
                  "argparser.error", "self.parser.error")

_REASONED_NOQA = re.compile(r"#\s*noqa:\s*BLE001\s*-\s*\S")


def _in_scope(mod: Module) -> bool:
    return any(mod.path.endswith(sf) for sf in SCOPE_FILES)


def _handler_disposes(handler: ast.ExceptHandler) -> bool:
    """True when the handler visibly routes the failure somewhere:
    raises, returns, breaks/continues out, or calls the shared 503
    builder."""
    stack = list(handler.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, (ast.Raise, ast.Return, ast.Continue, ast.Break)):
            return True
        if isinstance(n, ast.Call):
            name = call_name(n)
            if name is not None:
                if name.split(".")[-1] == BUILDER:
                    return True
                if name in TERMINAL_CALLS:
                    # parser.error()/ap.error() raises SystemExit
                    return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def _reasoned_suppression(mod: Module,
                          handler: ast.ExceptHandler) -> bool:
    lines = mod.source.splitlines()
    check = [handler.lineno]
    if handler.body:
        check.append(handler.body[0].lineno)
    for ln in check:
        if 0 < ln <= len(lines) and _REASONED_NOQA.search(lines[ln - 1]):
            return True
    return False


def _exc_token(handler: ast.ExceptHandler) -> str:
    t = handler.type
    if t is None:
        return "bare-except"
    if isinstance(t, ast.Tuple):
        parts = []
        for e in t.elts:
            parts.append(getattr(e, "attr", getattr(e, "id", "?")))
        return "+".join(parts)
    return getattr(t, "attr", getattr(t, "id", "?"))


def _clamped(value: ast.AST) -> bool:
    """Does the assigned value expression clamp through the shared cap?
    Accepts any expression that mentions both ``min(`` and the cap
    constant (``min(RETRY_AFTER_CAP_S, max(1, ...))``)."""
    has_min = False
    has_cap = False
    for n in ast.walk(value):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "min":
            has_min = True
        if isinstance(n, ast.Name) and CLAMP_NAME in n.id:
            has_cap = True
        if isinstance(n, ast.Attribute) and CLAMP_NAME in n.attr:
            has_cap = True
    return has_min and has_cap


def _check_retry_after(mod: Module, findings: list) -> None:
    """Repo-wide: ``...headers["Retry-After"] = <expr>`` must clamp
    unless it lives inside the shared builder itself."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.slice, ast.Constant)
                    and tgt.slice.value == "Retry-After"):
                continue
            scope = mod.scope_of(node)
            if scope.split(".")[-1] == BUILDER:
                if not _clamped(node.value):
                    findings.append(mod.finding(
                        RULE, node, "builder-unclamped",
                        f"the shared {BUILDER} builder no longer clamps "
                        f"Retry-After via min({CLAMP_NAME}, ...)"))
                continue
            findings.append(mod.finding(
                RULE, node, "retry-after-producer",
                f"Retry-After set outside the shared {BUILDER} builder "
                f"— route the DependencyUnavailable through it so the "
                f"[1, {CLAMP_NAME}] clamp cannot be missed"))


def run(modules) -> list:
    findings = []
    for mod in modules:
        if mod.tree is None:
            continue
        _check_retry_after(mod, findings)
        if not _in_scope(mod):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _handler_disposes(node) or _reasoned_suppression(mod, node):
                continue
            findings.append(mod.finding(
                RULE, node, f"swallowed-{_exc_token(node)}",
                f"except {_exc_token(node)} falls through without "
                f"raising, returning, or routing through {BUILDER} — "
                f"on the decision path a swallowed failure is a "
                f"fail-open verdict"))
    return findings
