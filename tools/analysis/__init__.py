"""Invariant lint suite: AST passes encoding the concurrency / fail-closed
/ jit-stability / metrics contracts this codebase already paid to learn
(see docs/development.md). Run via ``tools/analysis/run.py`` or
``make analyze``."""
