"""metrics-contract: every metric family registered consistently and
documented — in both directions.

The registry is get-or-create, so nothing at runtime stops two call
sites registering ``engine_checks_total`` once as a counter and once as
a gauge, or with different label keys — the scrape either breaks or
silently splits a family. And docs/operations.md is how operators find
families: an undocumented metric is invisible, a documented-but-removed
one is a broken dashboard.

Checks (code side = every ``*.counter/gauge/histogram("name", k=v…)``
call on a metrics-shaped receiver):

- literal names only — a computed name defeats this whole contract
- one kind per name across the repo
- one label-KEY set per name across the repo (values vary, keys must
  not: a label key present on some increments and absent on others
  splits the family into disjoint series)
- both directions vs the ``## Metrics reference`` table in
  docs/operations.md: every registered family has a row; every row
  names a registered family; row kind and label columns agree with code
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Set

from .core import Finding, Module, terminal_attr

RULE = "metrics-contract"

KINDS = ("counter", "gauge", "histogram")
NON_LABEL_KWARGS = {"buckets"}
DOCS_REL = "docs/operations.md"
SECTION = "## Metrics reference"


def _metrics_receiver(expr: ast.AST) -> bool:
    name = terminal_attr(expr)
    if name is None:
        return False
    low = name.lower()
    return "metric" in low or "registry" in low or low == "reg"


def _label_keys(call: ast.Call):
    """Label-key set for a registration call; handles ``**{"class": v}``
    splats with constant keys (``class`` is a Python keyword, so that's
    the only way to pass it)."""
    keys = set()
    for kw in call.keywords:
        if kw.arg is not None:
            if kw.arg not in NON_LABEL_KWARGS:
                keys.add(kw.arg)
        elif isinstance(kw.value, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in kw.value.keys):
            keys.update(k.value for k in kw.value.keys)
        else:
            return None  # opaque **splat: label set unknowable
    return frozenset(keys)


def _collect_code(modules):
    """name -> {kinds, labelsets, sites:[(mod,node)]}; plus dynamic
    sites."""
    fam: Dict[str, dict] = {}
    dynamic = []
    for mod in modules:
        if mod.tree is None:
            continue
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in KINDS
                    and _metrics_receiver(n.func.value)):
                continue
            if not n.args or not (isinstance(n.args[0], ast.Constant)
                                  and isinstance(n.args[0].value, str)):
                dynamic.append((mod, n))
                continue
            name = n.args[0].value
            labels = _label_keys(n)
            ent = fam.setdefault(name, {"kinds": set(), "labelsets": set(),
                                        "sites": []})
            ent["kinds"].add(n.func.attr)
            if labels is not None:
                ent["labelsets"].add(labels)
            ent["sites"].append((mod, n))
    return fam, dynamic


_ROW_RE = re.compile(
    r"^\|\s*`(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)`\s*\|\s*(?P<kind>\w+)\s*\|"
    r"\s*(?P<labels>[^|]*)\|")


def _parse_docs(root: str):
    """rows: name -> (kind, labelkeys, lineno); None when the section is
    missing entirely."""
    path = os.path.join(root, DOCS_REL)
    if not os.path.exists(path):
        return None
    rows: Dict[str, tuple] = {}
    in_section = False
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if line.startswith("## "):
                in_section = line.strip() == SECTION
                continue
            if not in_section:
                continue
            m = _ROW_RE.match(line)
            if not m:
                continue
            labels = {t.strip().strip("`") for t in
                      m.group("labels").split(",")}
            labels = {x for x in labels if x and x not in ("—", "-")}
            rows[m.group("name")] = (m.group("kind").lower(),
                                     frozenset(labels), i)
    return rows if rows else None


def _doc_finding(line: int, token: str, msg: str) -> Finding:
    return Finding(rule=RULE, path=DOCS_REL, line=line, scope="<doc>",
                   token=token, message=msg)


def run(modules, root: str) -> list:
    findings = []
    fam, dynamic = _collect_code(modules)
    for mod, n in dynamic:
        findings.append(mod.finding(
            RULE, n, "dynamic-name",
            "metric registered with a non-literal name — the "
            "kind/label/docs contract can't be checked; use a literal "
            "per family"))
    for name in sorted(fam):
        ent = fam[name]
        mod, node = ent["sites"][0]
        if len(ent["kinds"]) > 1:
            findings.append(mod.finding(
                RULE, node, f"kind-conflict-{name}",
                f"`{name}` registered as multiple kinds "
                f"({', '.join(sorted(ent['kinds']))}) — one family, one "
                f"kind"))
        if len(ent["labelsets"]) > 1:
            pretty = " vs ".join(
                "{" + ",".join(sorted(ls)) + "}"
                for ls in sorted(ent["labelsets"], key=sorted))
            findings.append(mod.finding(
                RULE, node, f"label-conflict-{name}",
                f"`{name}` registered with differing label-key sets "
                f"({pretty}) — a key present on some increments and "
                f"absent on others splits the family"))

    docs = _parse_docs(root)
    if docs is None:
        findings.append(_doc_finding(
            0, "missing-reference-section",
            f"{DOCS_REL} has no populated `{SECTION}` table — the "
            f"doc<->code family contract can't be checked"))
        return findings
    for name in sorted(fam):
        ent = fam[name]
        mod, node = ent["sites"][0]
        if name not in docs:
            findings.append(mod.finding(
                RULE, node, f"undocumented-{name}",
                f"`{name}` is registered but missing from the "
                f"`{SECTION}` table in {DOCS_REL}"))
            continue
        dkind, dlabels, dline = docs[name]
        kinds = ent["kinds"]
        if len(kinds) == 1 and dkind not in kinds:
            findings.append(_doc_finding(
                dline, f"doc-kind-{name}",
                f"docs say `{name}` is a {dkind}; code registers a "
                f"{next(iter(kinds))}"))
        code_labels = set().union(*ent["labelsets"])
        if len(ent["labelsets"]) == 1 and dlabels != code_labels:
            findings.append(_doc_finding(
                dline, f"doc-labels-{name}",
                f"docs label set {{{','.join(sorted(dlabels))}}} for "
                f"`{name}` disagrees with code "
                f"{{{','.join(sorted(code_labels))}}}"))
    for name in sorted(set(docs) - set(fam)):
        findings.append(_doc_finding(
            docs[name][2], f"stale-doc-{name}",
            f"docs table names `{name}` but no code registers it"))
    return findings
