"""Shared infrastructure for the invariant lint passes.

Every pass consumes parsed ``Module`` objects (source + AST with parent
links) and emits ``Finding``s. A finding's *fingerprint* deliberately
excludes the line number — ``rule|relpath|scope|token`` — so the
checked-in allowlist survives unrelated edits to the same file; ``scope``
is the enclosing function's qualified name (or ``<module>``) and
``token`` is a short, stable detail such as the offending call name.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # repo-relative, forward slashes
    line: int
    scope: str      # enclosing function qualname or <module>
    token: str      # short stable detail (e.g. the blocked call name)
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.token}"

    def render(self) -> str:
        return (f"{self.rule}: {self.path}:{self.line} [{self.scope}] "
                f"{self.message}")


class Module:
    """One parsed source file: AST with parent links plus lookup helpers."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        """Qualified name of the innermost enclosing function/class."""
        names: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(anc.name)
        return ".".join(reversed(names)) if names else "<module>"

    def finding(self, rule: str, node: ast.AST, token: str,
                message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 0),
                       scope=self.scope_of(node), token=token,
                       message=message)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def terminal_attr(node: ast.AST) -> Optional[str]:
    """Last attribute/name segment of an expression (``self.x.y`` -> y)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return terminal_attr(node.func)
    return None


def body_nodes(func: ast.AST, *, skip_nested: bool = True):
    """Walk a function body; nested function defs (of either flavor) are
    skipped — they execute in their own context (executor thunks, jit
    closures, callbacks) and are analyzed on their own when relevant."""
    stack = list(getattr(func, "body", []))
    while stack:
        node = stack.pop()
        if skip_nested and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# substring matches, plus "mu" as an EXACT name (the Go idiom) — a
# substring 'mu' would swallow names like "emulator"
LOCKISH_PARTS = ("lock", "guard", "mutex")
LOCKISH_EXACT = ("mu",)


def is_lockish(expr: ast.AST) -> bool:
    """Does a ``with`` context expression look like a named lock?
    Matches names/attrs containing lock/guard/mutex (``self._lock``,
    ``host_lock``, ``cg._host_guard()``) or exactly named ``mu``."""
    name = terminal_attr(expr)
    if name is None:
        return False
    low = name.lower()
    return any(p in low for p in LOCKISH_PARTS) \
        or low.lstrip("_") in LOCKISH_EXACT


def with_lock_items(node: ast.With) -> List[ast.AST]:
    return [item.context_expr for item in node.items
            if is_lockish(item.context_expr)]


def held_lock_names(mod: Module, node: ast.AST) -> List[str]:
    """Terminal names of lockish ``with`` contexts enclosing *node*
    (sync ``with`` only — ``async with`` guards an asyncio lock, which
    is await-safe by construction)."""
    held: List[str] = []
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for expr in with_lock_items(anc):
                held.append(terminal_attr(expr) or "?")
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break  # a lock held by a caller is out of static scope
    return held


# ---------------------------------------------------------------- files

DEFAULT_EXCLUDES = ("__pycache__", ".git", "tests/fixtures")


def iter_py_files(root: str, paths: Iterable[str],
                  excludes: Tuple[str, ...] = DEFAULT_EXCLUDES):
    """Yield (abspath, relpath-to-root) for every .py under *paths*."""
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield ap, os.path.relpath(ap, root)
            continue
        # an exclude already contained in the REQUESTED path doesn't
        # apply below it (asking for tests/fixtures/... means it)
        norm = os.path.relpath(ap, root).replace(os.sep, "/")
        eff = tuple(x for x in excludes if x not in norm)
        for dirpath, dirnames, filenames in os.walk(ap):
            rel = os.path.relpath(dirpath, root)
            dirnames[:] = [d for d in dirnames
                           if not any(x in os.path.join(rel, d)
                                      for x in eff)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    yield fp, os.path.relpath(fp, root)


def load_modules(root: str, paths: Iterable[str]) -> List[Module]:
    mods = []
    for ap, rp in iter_py_files(root, paths):
        with open(ap, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            mods.append(Module(ap, rp, src))
        except SyntaxError as e:  # surfaced as a finding, not a crash
            m = Module.__new__(Module)
            m.abspath, m.path, m.source = ap, rp.replace(os.sep, "/"), src
            m.tree = None
            m.syntax_error = e
            mods.append(m)
    return mods


# ------------------------------------------------------------ allowlist

@dataclass
class Allowlist:
    entries: Dict[str, str] = field(default_factory=dict)  # fp -> why
    used: set = field(default_factory=set)
    malformed: List[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        al = cls()
        if not os.path.exists(path):
            return al
        with open(path, "r", encoding="utf-8") as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if "#" not in line:
                    al.malformed.append(line)
                    continue
                fp, why = line.split("#", 1)
                fp, why = fp.strip(), why.strip()
                if not fp or not why or fp.count("|") != 3:
                    al.malformed.append(line)
                    continue
                al.entries[fp] = why
        return al

    def match(self, finding: Finding) -> bool:
        if finding.fingerprint in self.entries:
            self.used.add(finding.fingerprint)
            return True
        return False

    def stale(self) -> List[str]:
        return sorted(set(self.entries) - self.used)
