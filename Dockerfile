# The serving image deploy/proxy.yaml references as
# spicedb-kubeapi-proxy-tpu:latest (`make image`).
#
# CPU JAX by default so the image runs anywhere (development, the
# in-memory demo, CI). TPU node pools build with the TPU extra instead:
#
#   docker build --build-arg JAX_EXTRA="tpu" -t spicedb-kubeapi-proxy-tpu .
#
# The native graph-builder core is compiled in a throwaway stage; the
# runtime stage stays toolchain-free (ctypes loads the .so, with a numpy
# fallback if the build is skipped).

FROM python:3.11-slim AS native
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
COPY spicedb_kubeapi_proxy_tpu/native/graphcore.cpp /src/graphcore.cpp
RUN g++ -O3 -std=c++17 -fPIC -shared -pthread /src/graphcore.cpp \
    -o /src/libgraphcore.so

FROM python:3.11-slim
ARG JAX_EXTRA=cpu
RUN pip install --no-cache-dir "jax[${JAX_EXTRA}]" numpy pyyaml
WORKDIR /app
COPY spicedb_kubeapi_proxy_tpu /app/spicedb_kubeapi_proxy_tpu
COPY deploy /app/deploy
COPY --from=native /src/libgraphcore.so \
    /app/spicedb_kubeapi_proxy_tpu/native/libgraphcore.so
ENV PYTHONPATH=/app PYTHONUNBUFFERED=1
# /var/lib/proxy is the StatefulSet's persistent volume (dual-write DB,
# snapshots/WAL, discovery cache); create it so a volume-less `docker
# run` still works
RUN mkdir -p /var/lib/proxy
EXPOSE 8443
ENTRYPOINT ["python", "-m", "spicedb_kubeapi_proxy_tpu.proxy.cli"]
# no default CMD: deploy/proxy.yaml supplies the full flag set; a bare
# `docker run` prints the flag reference via --help
CMD ["--help"]
