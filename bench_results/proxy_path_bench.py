"""Full proxy-path list filter (VERDICT 'real HTTP round trip' milestone
context): the end-to-end cost of GET /api/v1/pods for 100k pods through
the REAL middleware — authorize() -> concurrent prefilter (device query
+ id->name mapping) -> upstream JSON body -> response filtering — vs the
engine-only figure bench.py reports.

    python bench_results/proxy_path_bench.py [n_pods] [trials]

Prints one JSON line with the stage breakdown.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build_engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize  # noqa: E402
from spicedb_kubeapi_proxy_tpu.authz.lookups import (  # noqa: E402
    run_prefilter_sync,
)
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (  # noqa: E402
    parse_request_info,
)
from spicedb_kubeapi_proxy_tpu.proxy.types import (  # noqa: E402
    ProxyRequest,
    ProxyResponse,
)
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules.matcher import RequestMeta  # noqa: E402

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: pods
  verbs: ["list"]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources:
    tpl: "pod:$#view@user:{{user.name}}"
"""


async def main() -> None:
    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    trials = int(sys.argv[2]) if len(sys.argv) > 2 else 7
    # quick-config density scaled up: enough rels that a user sees a
    # meaningful slice of the list
    engine, n_rels = build_engine(n_pods, 500, 20, 50,
                                  max(50_000, 5 * n_pods))

    # upstream body: the full pod list, built once (the fake apiserver's
    # own serialization cost is out of scope — kube pays it upstream)
    items = [{"apiVersion": "v1", "kind": "Pod",
              "metadata": {"name": f"p{i}", "namespace": "ns"}}
             for i in range(n_pods)]
    body = json.dumps({"kind": "PodList", "apiVersion": "v1",
                       "items": items}).encode()

    async def upstream(req):
        return ProxyResponse(
            status=200, headers={"Content-Type": "application/json"},
            body=body)

    matcher = MapMatcher.from_yaml(RULES)
    deps = AuthzDeps(matcher=matcher, engine=engine, upstream=upstream)
    info = parse_request_info("GET", "/api/v1/pods", {})
    req = ProxyRequest(method="GET", path="/api/v1/pods", query={},
                       headers={}, body=b"",
                       user=UserInfo(name="u7"), request_info=info)

    # warm (jit compile + caches)
    resp = await authorize(req, deps)
    assert resp.status == 200, resp.status
    kept = len(json.loads(resp.body)["items"])

    walls = []
    for _ in range(trials):
        t0 = time.perf_counter()
        resp = await authorize(req, deps)
        walls.append(time.perf_counter() - t0)
    walls.sort()

    # stage attribution (sequential, outside the overlap): prefilter
    # alone, and the body filter alone
    pf = matcher.match(RequestMeta(
        verb="list", api_group="", api_version="v1",
        resource="pods"))[0].pre_filters[0]
    from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput
    from spicedb_kubeapi_proxy_tpu.rules.input import RequestInfo as RI

    input = ResolveInput.create(
        RI(verb="list", api_version="v1", resource="pods",
           path="/api/v1/pods"), UserInfo(name="u7"))
    t0 = time.perf_counter()
    allowed = run_prefilter_sync(engine, pf, input)
    t_prefilter = time.perf_counter() - t0
    from spicedb_kubeapi_proxy_tpu.authz.filterer import filter_body

    t0 = time.perf_counter()
    filter_body(body, allowed, input)
    t_filter = time.perf_counter() - t0

    print(json.dumps({
        "n_pods": n_pods, "n_rels": int(n_rels), "kept": kept,
        "allowed": len(allowed), "trials": trials,
        "proxy_path_p50_ms": round(walls[len(walls) // 2] * 1e3, 1),
        "proxy_path_min_ms": round(walls[0] * 1e3, 1),
        "prefilter_ms": round(t_prefilter * 1e3, 1),
        "json_body_filter_ms": round(t_filter * 1e3, 1),
    }))


asyncio.run(main())
