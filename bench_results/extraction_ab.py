"""Stage-0 chip micro: A/B the final mask-read extraction, ~2 min total.

    python bench_results/extraction_ab.py [n_pods] [n_rels] [trials]

Window #1's trace showed the general fancy-index gather costing 0.95 ms
of the 3.04 ms device time (31%) for the list-filter shape; the
contiguous-window `dynamic_slice` fast path replaced it afterwards and
has never run on a chip. This script builds a mid-size graph (~30 s
host-side), then measures the SAME query with the fast path on and off,
amortizing the tunnel RTT by dispatching each trial's queries
back-to-back asynchronously — the A-B delta isolates the extraction op
without needing the full headline run. Emits one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> None:
    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    n_rels = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000
    trials = int(sys.argv[3]) if len(sys.argv) > 3 else 15
    burst = 8  # queries dispatched back-to-back per timed trial

    sys.path.insert(0, ".")
    import os

    import jax

    # the image's sitecustomize overrides platform selection to the axon
    # plugin, which HANGS when the tunnel is down — honor an explicit
    # JAX_PLATFORMS=cpu (validation runs) the way tests/conftest.py does
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.models import parse_schema

    rng = np.random.default_rng(7)
    e = Engine(schema=parse_schema("""
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  permission view = namespace->view
}
"""))
    n_ns, n_users = max(n_pods // 10, 100), 1000
    cols = []
    m_ns = max(n_rels - n_pods, n_ns)
    cols.append(("namespace",
                 np.char.add("ns", rng.integers(n_ns, size=m_ns).astype(str)),
                 "viewer", "user",
                 np.char.add("u", rng.integers(n_users, size=m_ns).astype(str))))
    cols.append(("pod", np.char.add("p", np.arange(n_pods).astype(str)),
                 "namespace", "namespace",
                 np.char.add("ns", rng.integers(n_ns, size=n_pods).astype(str))))
    merged = {
        "resource_type": np.concatenate(
            [np.full(len(c[1]), c[0]) for c in cols]),
        "resource_id": np.concatenate([c[1] for c in cols]),
        "relation": np.concatenate(
            [np.full(len(c[1]), c[2]) for c in cols]),
        "subject_type": np.concatenate(
            [np.full(len(c[1]), c[3]) for c in cols]),
        "subject_id": np.concatenate([c[4] for c in cols]),
        "subject_relation": np.concatenate(
            [np.full(len(c[1]), "") for c in cols]),
    }
    t0 = time.time()
    e.bulk_load(merged)
    cg = e.compiled()
    objs = e._objects_by_name()
    print(f"built {len(merged['resource_id'])} rels in {time.time()-t0:.0f}s "
          f"(backend {jax.default_backend()})", file=sys.stderr)

    off = cg.offset_of("pod", "view")
    n = cg.type_sizes["pod"]
    qs = off + np.arange(n, dtype=np.int32)
    qb = np.zeros(n, dtype=np.int32)
    subs = [np.asarray([cg.encode_subject("user", f"u{i}", None, objs)],
                       dtype=np.int32) for i in range(burst)]

    def measure(contig: bool) -> float:
        # warm the trace
        cg.query_async(subs[0], qs, qb, q_contiguous=contig,
                       q_cache_key=("ab", off, n, contig)).result()
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            futs = [cg.query_async(s, qs, qb, q_contiguous=contig,
                                   q_cache_key=("ab", off, n, contig))
                    for s in subs]
            for f in futs:
                f.result()
            lat.append((time.perf_counter() - t0) * 1e3 / burst)
        lat.sort()
        return lat[len(lat) // 2]

    p50_slice = measure(True)
    p50_gather = measure(False)
    out = {
        "backend": jax.default_backend(),
        "n_pods": n_pods, "n_rels": int(len(merged["resource_id"])),
        "burst": burst, "trials": trials,
        "amortized_ms_gather": round(p50_gather, 3),
        "amortized_ms_slice": round(p50_slice, 3),
        "delta_ms": round(p50_gather - p50_slice, 3),
        "note": "per-query amortized over async bursts (tunnel RTT "
                "cancelled); delta isolates the extraction op — window-1 "
                "trace predicts ~0.9ms on a v5e at 131072 pods",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
