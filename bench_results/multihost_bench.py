"""Multi-host serving bench (VERDICT r4 directive 4 / Weak #3).

Measures, on CPU with real TCP mirror subscribers on loopback:

- leader check_bulk throughput with 0/1/3 followers subscribed
  (follower replay cost lands on the follower's OWN host in production,
  so subscribers here count bytes and discard — the measurement isolates
  the leader-side publish + wire cost);
- mirror-wire bytes per 512-check frame and bytes/sec at a follower;
- the leader-lock ceiling: aggregate throughput from 1/4/8 concurrent
  request threads against the serialized MirroredEngine, vs the plain
  engine's own concurrency.

    python bench_results/multihost_bench.py [trials]

Prints one JSON line.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import struct
import sys
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build_engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine import CheckItem  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.remote import (  # noqa: E402
    EngineServer,
    _pack,
)
from spicedb_kubeapi_proxy_tpu.engine.store import WriteOp  # noqa: E402
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    Relationship,
)
from spicedb_kubeapi_proxy_tpu.parallel.multihost import (  # noqa: E402
    MirroredEngine,
)


class ByteCountingSubscriber:
    """Raw mirror subscriber: reads and discards frames, counting bytes
    and frames (a production follower replays on its own host's CPU)."""

    def __init__(self, port: int):
        self.bytes = 0
        self.frames = 0
        self._s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self._s.sendall(_pack({"op": "mirror_subscribe"}))
        self._read_frame()  # ack
        self.bytes = 0  # don't count the ack
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _read_frame(self) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = self._s.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionResetError
            hdr += chunk
        (n,) = struct.unpack(">I", hdr)
        body = b""
        while len(body) < n:
            chunk = self._s.recv(n - len(body))
            if not chunk:
                raise ConnectionResetError
            body += chunk
        self.bytes += 4 + n
        return body

    def _drain(self):
        try:
            while True:
                self._read_frame()
                self.frames += 1
        except OSError:
            pass

    def close(self):
        try:
            self._s.close()
        except OSError:
            pass


def seq_throughput(engine, items, trials) -> float:
    engine.check_bulk(items)  # warm
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        engine.check_bulk(items)
        rates.append(len(items) / (time.perf_counter() - t0))
    rates.sort()
    return rates[len(rates) // 2]


def threaded_throughput(engine, items, n_threads, seconds=2.0) -> float:
    stop = time.perf_counter() + seconds
    counts = [0] * n_threads

    def worker(i):
        while time.perf_counter() < stop:
            engine.check_bulk(items)
            counts[i] += 1

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(n_threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.perf_counter() - t0
    return sum(counts) * len(items) / dt


def main() -> None:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 9
    n_pods, n_users = 2000, 500
    inner, _ = build_engine(n_pods, n_users, 20, 50, 50000)
    rng = np.random.default_rng(11)
    # same mix as bench.py's bulk-check section (8 subjects x 64 objects)
    # so the plain-engine figure is comparable with BENCH_r*.json
    items = [
        CheckItem("pod", f"ns/p{rng.integers(n_pods)}", "view",
                  "user", f"u{b}")
        for b in rng.integers(n_users, size=8)
        for _ in range(64)
    ]

    out: dict = {"checks_per_batch": len(items), "trials": trials}
    out["plain_seq_checks_per_s"] = round(seq_throughput(
        inner, items, trials))

    leader = MirroredEngine(inner)
    loop_holder = {}

    async def serve():
        srv = EngineServer(leader, port=0)
        port = await srv.start()
        loop_holder["port"] = port
        loop_holder["srv"] = srv
        loop_holder["loop"] = asyncio.get_running_loop()
        loop_holder["stop"] = asyncio.Event()
        loop_holder["ready"].set()
        await loop_holder["stop"].wait()
        await srv.stop()

    loop_holder["ready"] = threading.Event()
    st = threading.Thread(target=lambda: asyncio.run(serve()), daemon=True)
    st.start()
    assert loop_holder["ready"].wait(30)
    port = loop_holder["port"]

    subs: list[ByteCountingSubscriber] = []
    out["mirror_seq_checks_per_s"] = {}
    for n_followers in (0, 1, 3):
        while len(subs) < n_followers:
            subs.append(ByteCountingSubscriber(port))
            time.sleep(0.2)
        rate = seq_throughput(leader, items, trials)
        out["mirror_seq_checks_per_s"][str(n_followers)] = round(rate)
        if n_followers == 1:
            # wire cost at a realistic mix: checks + occasional writes
            s0 = subs[0]
            b0, f0 = s0.bytes, s0.frames
            t0 = time.perf_counter()
            for i in range(trials):
                leader.check_bulk(items)
                if i % 3 == 0:
                    leader.write_relationships([WriteOp(
                        "touch", Relationship(
                            "pod", f"ns/p{i}", "viewer", "user", "u1"))])
            dt = time.perf_counter() - t0
            time.sleep(0.3)  # let the stream drain
            out["wire_bytes_per_s"] = round((s0.bytes - b0) / dt)
            out["wire_frames"] = s0.frames - f0
            out["wire_bytes_per_frame"] = round(
                (s0.bytes - b0) / max(1, s0.frames - f0))
    out["lock_ceiling_checks_per_s"] = {}
    for n_threads in (1, 4, 8):
        out["lock_ceiling_checks_per_s"][str(n_threads)] = round(
            threaded_throughput(leader, items, n_threads))
    out["plain_threads8_checks_per_s"] = round(
        threaded_throughput(inner, items, 8))

    for s in subs:
        s.close()
    loop_holder["loop"].call_soon_threadsafe(loop_holder["stop"].set)
    st.join(15)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
