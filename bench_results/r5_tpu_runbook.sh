#!/bin/bash
# Round-5 TPU-window runbook: run EVERYTHING directive 1 needs the moment
# the axon tunnel comes back, archiving as it goes (the tunnel has
# multi-hour outages — front-load the valuable runs).
#
#   bash bench_results/r5_tpu_runbook.sh
#
# Produces, under bench_results/ (window-#1 artifacts r5_tpu_full.json /
# r5_tpu_profile/ are committed history; this writes fresh names):
#   r5_tpu_headline.json    stage 1: complete headline-only JSON (banked
#                           first — windows have closed mid-run)
#   r5_tpu_full2.json       stage 2: suite configs + remote-compare
#   r5_tpu_profile2/        stage-2 profiler trace — summarize with
#                           python bench_results/trace_optable.py
#   r5_tpu_*stderr*.log     full methodology logs
set -u
cd "$(dirname "$0")/.."
# persistent XLA compile cache: stage 2 (and any re-run) reuses stage 1's
# compiles instead of re-paying the ~55s tunnel-side warmup per process
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/sdbkp_jaxcache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

echo "== probing tunnel (subprocess, hard timeout) =="
timeout 150 python - <<'EOF'
import subprocess, sys
p = subprocess.run([sys.executable, "-c", "import jax; print(jax.devices())"],
                   capture_output=True, text=True, timeout=130)
sys.stdout.write(p.stdout)
sys.exit(0 if "tpu" in p.stdout.lower() or "axon" in p.stdout.lower() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "tunnel still down; not burning the window budget"; exit 1
fi

# Stage 0: extraction A/B (~2 min incl. build) — confirms the
# contiguous-window dynamic_slice win (window-1 trace predicts ~0.9 ms
# of 3.04 ms device time) even if the window closes before stage 1.
echo "== stage 0: extraction A/B micro =="
timeout 420 python bench_results/extraction_ab.py \
    > bench_results/r5_tpu_extraction_ab.json \
    2> bench_results/r5_tpu_extraction_ab_stderr.log
echo "stage 0 rc=$?"
cat bench_results/r5_tpu_extraction_ab.json 2>/dev/null
echo

# Stage 1: headline only (~6 min of tunnel time). Windows have closed
# mid-run before (window #1 hung at ~11 min, turning the suite run into a
# watchdog-partial) — bank a COMPLETE headline JSON before anything else.
echo "== stage 1: headline only =="
python bench.py --deadline 1150 \
    > bench_results/r5_tpu_headline.json 2> bench_results/r5_tpu_headline_stderr.log
echo "stage 1 rc=$?"
cat bench_results/r5_tpu_headline.json
echo

# Stage 2: the full suite + profile + remote-compare (rebuilds the graph,
# ~3 min overhead; worth it for stage isolation). Window-#1 artifacts
# (r5_tpu_full.json / r5_tpu_profile/) are committed history — write
# window-#2 outputs to their own names.
echo "== stage 2: full suite + profile + remote-compare =="
python bench.py --suite --remote-compare \
    --profile-dir bench_results/r5_tpu_profile2 \
    > bench_results/r5_tpu_full2.json 2> bench_results/r5_tpu_stderr2.log
rc=$?
echo "bench rc=$rc"
tail -40 bench_results/r5_tpu_stderr2.log
cat bench_results/r5_tpu_full2.json
echo
echo "== done; commit the artifacts =="
