#!/bin/bash
# Round-5 TPU-window runbook: run EVERYTHING directive 1 needs the moment
# the axon tunnel comes back, archiving as it goes (the tunnel has
# multi-hour outages — front-load the valuable runs).
#
#   bash bench_results/r5_tpu_runbook.sh
#
# Produces, under bench_results/:
#   r5_tpu_full.json        headline + suite configs (incl. post-closure
#                           config 3) + remote-compare + tail diagnosis
#   r5_tpu_profile/         jax profiler trace of the headline loop
#                           (fixpoint annotated "sdbkp:fixpoint" — answers
#                           the 150-vs-819 GB/s bandwidth question)
#   r5_tpu_stderr.log       full methodology log
set -u
cd "$(dirname "$0")/.."

echo "== probing tunnel (subprocess, hard timeout) =="
timeout 150 python - <<'EOF'
import subprocess, sys
p = subprocess.run([sys.executable, "-c", "import jax; print(jax.devices())"],
                   capture_output=True, text=True, timeout=130)
sys.stdout.write(p.stdout)
sys.exit(0 if "tpu" in p.stdout.lower() or "axon" in p.stdout.lower() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "tunnel still down; not burning the window budget"; exit 1
fi

echo "== full suite + profile + remote-compare (one engine build) =="
python bench.py --suite --remote-compare \
    --profile-dir bench_results/r5_tpu_profile \
    > bench_results/r5_tpu_full.json 2> bench_results/r5_tpu_stderr.log
rc=$?
echo "bench rc=$rc"
tail -40 bench_results/r5_tpu_stderr.log
cat bench_results/r5_tpu_full.json
echo
echo "== done; commit the artifacts =="
