"""Summarize a jax profiler trace into a per-op device-time table.

    python bench_results/trace_optable.py <dir-or-trace.json.gz> [trials]

Given a profile dir (bench --profile-dir) or a vm.trace.json.gz path,
prints device ops sorted by total time with per-trial ms, bytes accessed,
and effective GB/s — the table behind r5_tpu_trace_analysis.md, so the
next chip window's before/after comparison is one command per trace.
``trials`` defaults to the modal op count (each latency-loop trial runs
every op once).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def find_trace(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(
        os.path.join(path, "plugins", "profile", "*", "*.trace.json.gz")))
    if not hits:
        raise SystemExit(f"no *.trace.json.gz under {path}")
    return hits[-1]


def main() -> None:
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    trace = find_trace(sys.argv[1])
    with gzip.open(trace) as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    names = {e["pid"]: e["args"].get("name") for e in ev
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, n in names.items() if n and "TPU" in n.upper()}
    if not dev_pids:
        dev_pids = {p for p, n in names.items()
                    if n and "CPU" not in n.upper()}
    rows: dict[str, list] = {}
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        if name.startswith("jit__"):  # wrapper span double-counts children
            continue
        args = e.get("args") or {}
        r = rows.setdefault(name, [0, 0, 0,
                                   args.get("hlo_category", ""),
                                   (args.get("source", "") or "")
                                   .split("/")[-1]])
        r[0] += e.get("dur", 0)
        r[1] += 1
        r[2] += int(args.get("bytes_accessed", 0))
    if not rows:
        raise SystemExit("no device ops in trace")
    trials = (int(sys.argv[2]) if len(sys.argv) > 2
              else collections.Counter(
                  r[1] for r in rows.values()).most_common(1)[0][0])
    print(f"# {trace}  (trials={trials})")
    print(f"{'op':<26}{'ms/trial':>10}{'MB':>9}{'GB/s':>8}  category source")
    tot_ms = tot_b = 0.0
    for name, (dur, k, b, cat, src) in sorted(
            rows.items(), key=lambda kv: -kv[1][0]):
        ms = dur / trials / 1e3
        per_trial_b = b / trials
        tot_ms += ms
        tot_b += per_trial_b
        if ms < 0.005:
            continue
        gbps = (per_trial_b / 1e6) / ms if ms else 0
        print(f"{name:<26}{ms:10.3f}{per_trial_b / 1e6:9.2f}{gbps:8.1f}"
              f"  {cat} {src}")
    print(f"\nTOTAL device ms/trial = {tot_ms:.2f}, "
          f"bytes/trial = {tot_b / 1e6:.1f} MB, "
          f"effective = {tot_b / 1e6 / tot_ms if tot_ms else 0:.0f} GB/s")


if __name__ == "__main__":
    main()
