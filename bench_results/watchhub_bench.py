"""Watch-hub scale bench (VERDICT r4 directive 8).

W watchers across G (prefilter rule, subject) groups on ONE engine,
driven through the real middleware watch path (authorize -> filtered
stream) against the in-memory upstream. Measures, per relevant write:

- device recomputes (engine_lookups_total delta) — the O(groups) claim;
- frames/sec delivered across all watchers;
- event->frame latency (grant write -> flushed frame at every watcher
  of the granted subject), p50/p99 over E events;

both with the in-process engine and over a tcp:// engine host (one
server-push subscription per proxy, binary mask wire recomputes).

    python bench_results/watchhub_bench.py [watchers] [groups] [events]

Prints one JSON line.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.remote import (  # noqa: E402
    EngineServer,
    RemoteEngine,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.proxy.inmemkube import InMemoryKube  # noqa: E402
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (  # noqa: E402
    parse_request_info,
)
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["list", "watch"]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources:
    tpl: "namespace:$#view@user:{{user.name}}"
"""


async def run_mode(engine_for_proxy, inner: Engine, kube: InMemoryKube,
                   n_watchers: int, n_groups: int, n_events: int) -> dict:
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(RULES),
                     engine=engine_for_proxy, upstream=kube,
                     watch_poll_interval=0.05)
    frames_per: list[int] = [0] * n_watchers
    seen_per: list[set] = [set() for _ in range(n_watchers)]
    tasks = []
    streams = []

    async def consume(i, stream):
        async for f in stream:
            frames_per[i] += 1
            try:
                ev = json.loads(f)
                seen_per[i].add(ev["object"]["metadata"]["name"])
            except ValueError:
                pass

    for i in range(n_watchers):
        user = f"u{i % n_groups}"
        info = parse_request_info("GET", "/api/v1/namespaces",
                                  {"watch": ["true"]})
        req = ProxyRequest(method="GET", path="/api/v1/namespaces",
                           query={"watch": ["true"]}, headers={},
                           body=b"", user=UserInfo(name=user),
                           request_info=info)
        resp = await authorize(req, deps)
        assert resp.status == 200 and resp.stream is not None, resp.status
        streams.append(resp.stream)
        tasks.append(asyncio.ensure_future(consume(i, resp.stream)))
    # let registrations land (one hub group per distinct user)
    hub = deps.watch_hub
    deadline = time.monotonic() + 60
    while sum(len(g.watchers) for g in hub._groups.values()) < n_watchers:
        assert time.monotonic() < deadline, "watchers never registered"
        await asyncio.sleep(0.05)
    n_hub_groups = len(hub._groups)
    await asyncio.sleep(0.5)  # drain initial recomputes/frames

    lookups0 = metrics.counter("engine_lookups_total").value
    frames0 = sum(frames_per)
    lat = []
    t_all0 = time.monotonic()
    for e in range(n_events):
        g = e % n_groups
        name = f"ev{e}"
        watchers_of_g = [i for i in range(n_watchers)
                         if i % n_groups == g]
        # upstream object appears first (buffered: nobody allowed yet),
        # then the grant write flushes it — event->frame latency covers
        # write -> recompute -> flush at EVERY watcher of the group
        kube.put("namespaces", name)
        await asyncio.sleep(0)
        t0 = time.monotonic()
        await asyncio.to_thread(
            inner.write_relationships,
            [WriteOp("touch", parse_relationship(
                f"namespace:{name}#viewer@user:u{g}"))])
        deadline = time.monotonic() + 30
        while not all(name in seen_per[i] for i in watchers_of_g):
            assert time.monotonic() < deadline, \
                f"event {e} never reached all watchers of group {g}"
            await asyncio.sleep(0.002)
        lat.append(time.monotonic() - t0)
    dt_all = time.monotonic() - t_all0
    await asyncio.sleep(0.3)
    recomputes = metrics.counter("engine_lookups_total").value - lookups0
    frames = sum(frames_per) - frames0
    lat.sort()
    for t in tasks:
        t.cancel()
    kube.stop_watches()
    await asyncio.sleep(0.2)
    return {
        "hub_groups": n_hub_groups,
        "events": n_events,
        "recomputes": recomputes,
        "recomputes_per_event": round(recomputes / n_events, 2),
        "frames_delivered": frames,
        "frames_per_s": round(frames / dt_all),
        "events_per_s": round(n_events / dt_all, 1),
        "latency_ms_p50": round(lat[len(lat) // 2] * 1e3, 1),
        "latency_ms_p99": round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))] * 1e3, 1),
    }


async def main() -> None:
    # the tcp mode runs CLIENT AND SERVER on one loop sharing one default
    # executor; with hundreds of watchers the client-side to_thread calls
    # can occupy every worker while the server needs one to answer — a
    # same-pool deadlock impossible across real processes. Size the pool
    # past the watcher count so the bench measures the hub, not the pool.
    from concurrent.futures import ThreadPoolExecutor

    n_watchers = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    n_groups = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    n_events = int(sys.argv[3]) if len(sys.argv) > 3 else 30
    asyncio.get_running_loop().set_default_executor(
        ThreadPoolExecutor(max_workers=n_watchers + 64))
    out = {"watchers": n_watchers, "groups": n_groups}

    # -- in-process engine ------------------------------------------------
    inner = Engine()
    inner.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:seed#creator@user:u0"))])
    out["in_process"] = await run_mode(
        inner, inner, InMemoryKube(), n_watchers, n_groups, n_events)

    # -- tcp:// engine host (push watch, mask wire) -----------------------
    inner2 = Engine()
    inner2.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:seed#creator@user:u0"))])
    srv = EngineServer(inner2, port=0)
    port = await srv.start()
    remote = RemoteEngine("127.0.0.1", port)
    out["tcp_push"] = await run_mode(
        remote, inner2, InMemoryKube(), n_watchers, n_groups, n_events)
    remote.close()
    await srv.stop()
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(main())
