"""Micro-bench: prefilter id→(ns, name) mapping cost at 100k allowed ids
(the proxy-side cost of a big list filter that bench.py's direct mask
query does not include). Compares the fast paths against general
expression evaluation.

    python bench_results/prefilter_mapping_micro.py
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules.expr import (  # noqa: E402
    compile_template,
)
from spicedb_kubeapi_proxy_tpu.rules.input import (  # noqa: E402
    RequestInfo,
    ResolveInput,
    UserInfo,
)

N = 100_000
ids = [f"ns{i % 50}/pod-{i}" for i in range(N)]
input = ResolveInput.create(
    RequestInfo(verb="list", api_version="v1", resource="pods",
                path="/api/v1/pods"),
    UserInfo(name="alice"))
base = input.template_data()


def timed(label, fn, trials=5):
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return label, round(best * 1e3, 1)


def general_copy():
    """The pre-round-5 general loop: dict copy + expr eval per id."""
    name_expr = compile_template("{{split_name(resourceId)}}")
    ns_expr = compile_template("{{split_namespace(resourceId)}}")
    allowed = AllowedSet()
    for obj_id in ids:
        data = dict(base)
        data["resourceId"] = obj_id
        allowed.add(ns_expr.evaluate_str(data),
                    name_expr.evaluate_str(data))
    return allowed


def general_reuse():
    """The round-5 general loop: one reused dict."""
    name_expr = compile_template("{{split_name(resourceId)}}")
    ns_expr = compile_template("{{split_namespace(resourceId)}}")
    allowed = AllowedSet()
    pairs = allowed.pairs
    data = dict(base)
    ne, se = name_expr.evaluate_str, ns_expr.evaluate_str
    for obj_id in ids:
        data["resourceId"] = obj_id
        pairs.add((se(data) or "", ne(data)))
    return allowed


def fast_split():
    """The round-5 fast path for the split form."""
    allowed = AllowedSet()
    pairs = allowed.pairs
    for obj_id in ids:
        ns, sep, nm = obj_id.partition("/")
        pairs.add((ns, nm) if sep else ("", obj_id))
    return allowed


def fast_identity():
    allowed = AllowedSet()
    allowed.pairs.update(("", i) for i in ids)
    return allowed


assert general_copy().pairs == general_reuse().pairs == fast_split().pairs

out = dict([
    timed("general_copy_ms", general_copy),
    timed("general_reuse_ms", general_reuse),
    timed("fast_split_ms", fast_split),
    timed("fast_identity_ms", fast_identity),
])
out["n_ids"] = N
print(json.dumps(out))
