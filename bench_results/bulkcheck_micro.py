"""Micro-bench for the bulk-check host path (VERDICT r4 Weak #2).

Reproduces only the `bulk check` section of bench.py --quick, with many
trials so noise is quantified. Run on CPU:

    JAX_PLATFORMS=cpu python bench_results/bulkcheck_micro.py [trials]

Prints one JSON line: {"p50_us_per_check": ..., "checks_per_s": [...]}.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Force CPU even though the axon sitecustomize pins JAX_PLATFORMS=axon at
# interpreter startup (same dance as tests/conftest.py — backends are lazy,
# so flipping the config before any computation keeps us off the tunnel).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build_engine  # noqa: E402


def main() -> None:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 15
    n_pods, n_users, n_ns, n_groups, n_rels = 2000, 500, 20, 50, 50000
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels)

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem

    rng = np.random.default_rng(7)
    B, per = 8, 64
    items = [
        CheckItem("pod", f"ns/p{rng.integers(n_pods)}", "view",
                  "user", f"u{b}")
        for b in rng.integers(n_users, size=B)
        for _ in range(per)
    ]
    e.check_bulk(items)  # warmup (jit compile + caches)
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        e.check_bulk(items)
        dt = time.perf_counter() - t0
        rates.append(len(items) / dt)
    rates.sort()
    p50 = rates[len(rates) // 2]
    out = {
        "n_checks": len(items),
        "trials": trials,
        "p50_checks_per_s": round(p50),
        "min_checks_per_s": round(rates[0]),
        "max_checks_per_s": round(rates[-1]),
        "p50_us_per_check": round(1e6 / p50, 3),
        "rates": [round(r) for r in rates],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
